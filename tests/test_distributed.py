"""Distributed stack tests on the 8-device virtual CPU mesh
(reference test pattern: SURVEY.md §4 — multi-rank on one host)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


@pytest.fixture
def hcg():
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 1, "sharding_degree": 2,
                               "sep_degree": 1}
    h = fleet.init(is_collective=True, strategy=strategy)
    yield h
    dist.set_hybrid_communicate_group(None)


class TestTopology:
    def test_mesh_axes(self, hcg):
        assert hcg.mesh.shape == {"pp": 1, "dp": 2, "sharding": 2,
                                  "sep": 1, "mp": 2}
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.nranks == 8

    def test_groups(self, hcg):
        g = hcg.get_model_parallel_group()
        assert g.nranks == 2 and g.axis_name == "mp"
        dp = hcg.get_data_parallel_group()
        assert dp.nranks == 2

    def test_topology_math(self):
        topo = dist.CommunicateTopology(
            ["pipe", "data", "sharding", "sep", "model"], [2, 2, 1, 1, 2])
        assert topo.world_size() == 8
        assert topo.get_rank(pipe=1, data=0, sharding=0, sep=0, model=1) == 5
        groups = topo.get_comm_list("model")
        assert all(len(g) == 2 for g in groups)


class TestAutoParallel:
    def test_shard_tensor_and_reshard(self):
        mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4),
                                dim_names=["x", "y"])
        t = paddle.randn([8, 16])
        st = dist.shard_tensor(t, mesh, [dist.Shard(0), dist.Shard(1)])
        v = st._value
        assert isinstance(v.sharding, NamedSharding)
        assert v.sharding.spec == P("x", "y")
        # reshard to replicated
        r = dist.reshard(st, mesh, [dist.Replicate(), dist.Replicate()])
        assert r._value.sharding.spec == P()
        np.testing.assert_allclose(np.asarray(r._value), t.numpy())

    def test_shard_then_compute(self):
        mesh = dist.ProcessMesh(np.arange(8).reshape(8), dim_names=["x"])
        a = dist.shard_tensor(paddle.randn([16, 4]), mesh, [dist.Shard(0)])
        b = paddle.randn([4, 8])
        out = paddle.matmul(a, b)
        np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_shard_layer(self):
        mesh = dist.ProcessMesh(np.arange(8), dim_names=["x"])

        def shard_fn(name, layer, mesh):
            for pname, p in layer._parameters.items():
                if p is not None and p.ndim == 2:
                    dist.shard_tensor(p, mesh, [dist.Shard(1)])

        lin = nn.Linear(8, 16)
        dist.shard_layer(lin, mesh, shard_fn)
        assert lin.weight._value.sharding.spec == P(None, "x")
        out = lin(paddle.randn([2, 8]))
        assert out.shape == [2, 16]

    @pytest.mark.slow
    def test_shard_optimizer_states(self):
        mesh = dist.ProcessMesh(np.arange(8), dim_names=["x"])
        lin = nn.Linear(8, 8)
        dist.shard_tensor(lin.weight, mesh, [dist.Shard(0)])
        opt = paddle.optimizer.Adam(parameters=lin.parameters())
        dist.shard_optimizer(opt)
        (lin(paddle.randn([4, 8])) ** 2).sum().backward()
        opt.step()
        m1 = opt._accumulators["moment1"][id(lin.weight)]
        assert "x" in str(m1.sharding.spec)

    def test_dtensor_local_roundtrip(self):
        mesh = dist.ProcessMesh(np.arange(8), dim_names=["x"])
        t = dist.shard_tensor(paddle.randn([16, 2]), mesh, [dist.Shard(0)])
        local = dist.dtensor_to_local(t)
        assert local.shape == [2, 2]  # 16/8


class TestCollectivesInShardMap:
    """Collectives exercise the axis-name path under shard_map (the way the
    fleet trainers use them)."""

    def _mesh(self):
        return Mesh(np.array(jax.devices()[:8]), axis_names=("dp",))

    def test_all_reduce_psum(self):
        try:
            from jax import shard_map
        except ImportError:   # older jax: experimental
            from jax.experimental.shard_map import shard_map
        mesh = self._mesh()
        x = jnp.arange(8.0)

        def f(x):
            t = paddle.Tensor(x)
            dist.all_reduce(t, group=dist.new_group())
            return t._value

        out = shard_map(f, mesh=mesh, in_specs=P("dp"),
                        out_specs=P("dp"))(x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))

    def test_all_gather(self):
        try:
            from jax import shard_map
        except ImportError:   # older jax: experimental
            from jax.experimental.shard_map import shard_map
        mesh = self._mesh()
        x = jnp.arange(8.0)

        def f(x):
            t = paddle.Tensor(x)
            outs = []
            dist.all_gather(outs, t, group="dp")
            return jnp.concatenate([o._value for o in outs])

        out = shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)
        assert out.shape == (64,)

    def test_reduce_scatter(self):
        try:
            from jax import shard_map
        except ImportError:   # older jax: experimental
            from jax.experimental.shard_map import shard_map
        mesh = self._mesh()
        x = jnp.ones((64,))

        def f(x):
            t = paddle.Tensor(jnp.zeros((1,)))
            dist.reduce_scatter(t, paddle.Tensor(x), group="dp")
            return t._value

        out = shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 8.0))


class TestMPLayers:
    def test_column_row_parallel_matmul(self, hcg):
        col = dist.fleet.ColumnParallelLinear(16, 32, gather_output=False)
        row = dist.fleet.RowParallelLinear(32, 16, input_is_parallel=True)
        assert col.weight._value.sharding.spec == P(None, "mp")
        assert row.weight._value.sharding.spec == P("mp", None)
        x = paddle.randn([4, 16])
        out = row(col(x))
        assert out.shape == [4, 16]
        # numeric parity with the unsharded computation
        want = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
            @ row.weight.numpy() + row.bias.numpy()
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-4, atol=1e-4)
        out.sum().backward()
        assert col.weight.grad is not None
        assert row.weight.grad is not None

    def test_vocab_parallel_embedding(self, hcg):
        emb = dist.fleet.VocabParallelEmbedding(64, 16)
        assert emb.weight._value.sharding.spec == P("mp", None)
        ids = paddle.to_tensor(np.random.randint(0, 64, (2, 6)))
        out = emb(ids)
        assert out.shape == [2, 6, 16]
        np.testing.assert_allclose(out.numpy(),
                                   emb.weight.numpy()[ids.numpy()],
                                   rtol=1e-6)

    def test_parallel_cross_entropy(self, hcg):
        pce = dist.fleet.ParallelCrossEntropy()
        logits = paddle.randn([4, 32])
        labels = paddle.to_tensor(np.random.randint(0, 32, (4,)))
        loss = pce(logits, labels)
        want = F.cross_entropy(logits, labels, reduction="none").numpy()
        np.testing.assert_allclose(loss.numpy()[:, 0], want, rtol=1e-5,
                                   atol=1e-5)


class TestDataParallel:
    def test_dp_wrap_and_train(self, hcg):
        net = nn.Linear(4, 4)
        from paddle_tpu.distributed import fleet
        dp_net = fleet.distributed_model(net)
        opt = fleet.distributed_optimizer(
            paddle.optimizer.SGD(0.1, parameters=net.parameters()))
        x = paddle.randn([8, 4])
        loss = (dp_net(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        assert np.isfinite(float(loss.item()))


class TestSharding:
    def test_stage1_shards_moments(self, hcg):
        from paddle_tpu.distributed.fleet.sharding import \
            DygraphShardingOptimizer
        lin = nn.Linear(16, 16)
        opt = DygraphShardingOptimizer(
            paddle.optimizer.Adam(parameters=lin.parameters()))
        (lin(paddle.randn([4, 16])) ** 2).sum().backward()
        opt.step()
        m = opt._inner_opt._accumulators["moment1"][id(lin.weight)]
        assert "sharding" in str(m.sharding.spec)

    def test_stage3_shards_params(self, hcg):
        from paddle_tpu.distributed.fleet.sharding import shard_model_stage3
        lin = nn.Linear(16, 16)
        shard_model_stage3(lin)
        assert "sharding" in str(lin.weight._value.sharding.spec)
        out = lin(paddle.randn([2, 16]))
        assert out.shape == [2, 16]

    def test_group_sharded_parallel_api(self, hcg):
        from paddle_tpu.distributed.fleet.sharding import \
            group_sharded_parallel
        lin = nn.Linear(16, 16)
        opt = paddle.optimizer.Adam(parameters=lin.parameters())
        model, opt2, _ = group_sharded_parallel(lin, opt, "p_g_os")
        (model(paddle.randn([4, 16])) ** 2).sum().backward()
        opt2.step()


class TestDistCheckpoint:
    def test_save_load_roundtrip(self, tmp_path):
        mesh = dist.ProcessMesh(np.arange(8), dim_names=["x"])
        w = dist.shard_tensor(paddle.randn([16, 4]), mesh, [dist.Shard(0)])
        b = paddle.randn([4])
        state = {"w": w, "b": b}
        dist.save_state_dict(state, str(tmp_path))
        w2 = dist.shard_tensor(paddle.zeros([16, 4]), mesh,
                               [dist.Shard(0)])
        b2 = paddle.zeros([4])
        dist.load_state_dict({"w": w2, "b": b2}, str(tmp_path))
        np.testing.assert_allclose(w2.numpy(), w.numpy())
        np.testing.assert_allclose(b2.numpy(), b.numpy())

    def test_reshard_on_load(self, tmp_path):
        # save sharded over 8, load sharded over 2x4 — placement change
        mesh1 = dist.ProcessMesh(np.arange(8), dim_names=["x"])
        w = dist.shard_tensor(paddle.randn([8, 8]), mesh1, [dist.Shard(0)])
        dist.save_state_dict({"w": w}, str(tmp_path))
        mesh2 = dist.ProcessMesh(np.arange(8).reshape(2, 4),
                                 dim_names=["a", "b"])
        w2 = dist.shard_tensor(paddle.zeros([8, 8]), mesh2,
                               [dist.Shard(1), dist.Shard(0)])
        dist.load_state_dict({"w": w2}, str(tmp_path))
        np.testing.assert_allclose(w2.numpy(), w.numpy())


class TestCheckpointStreaming:
    """Async save + slice-streaming load (reference:
    load_state_dict.py:43 ReadItem plan; flex_checkpoint async save)."""

    def test_async_save_then_load(self, tmp_path):
        import paddle_tpu.distributed as dist
        mesh = dist.ProcessMesh(np.arange(8), dim_names=["x"])
        w = dist.shard_tensor(paddle.randn([16, 8]), mesh, [dist.Shard(0)])
        dist.save_state_dict({"w": w}, str(tmp_path), async_save=True)
        # load joins the in-flight write automatically
        w2 = dist.shard_tensor(paddle.zeros([16, 8]), mesh,
                               [dist.Shard(0)])
        dist.load_state_dict({"w": w2}, str(tmp_path))
        np.testing.assert_allclose(w2.numpy(), w.numpy())

    def test_streaming_load_reads_only_overlaps(self, tmp_path, monkeypatch):
        """Sharded targets must assemble per-shard slices, never the full
        global array."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.checkpoint import save_load as sl
        mesh1 = dist.ProcessMesh(np.arange(8), dim_names=["x"])
        w = dist.shard_tensor(paddle.randn([8, 8]), mesh1, [dist.Shard(0)])
        dist.save_state_dict({"w": w}, str(tmp_path))

        calls = {"full": 0, "slice": 0}
        orig_full, orig_slice = sl._assemble, sl._assemble_slice

        def spy_full(*a, **k):
            calls["full"] += 1
            return orig_full(*a, **k)

        def spy_slice(*a, **k):
            calls["slice"] += 1
            return orig_slice(*a, **k)
        monkeypatch.setattr(sl, "_assemble", spy_full)
        monkeypatch.setattr(sl, "_assemble_slice", spy_slice)

        mesh2 = dist.ProcessMesh(np.arange(8).reshape(2, 4),
                                 dim_names=["a", "b"])
        w2 = dist.shard_tensor(paddle.zeros([8, 8]), mesh2,
                               [dist.Shard(1), dist.Shard(0)])
        dist.load_state_dict({"w": w2}, str(tmp_path))
        np.testing.assert_allclose(w2.numpy(), w.numpy())
        assert calls["full"] == 0, "full-array assembly used for sharded target"
        assert calls["slice"] >= 1


class TestUtilBaseAllReduceIntegerExactness:
    """ADVICE r5: UtilBase.all_reduce round-tripped every reduction
    through float32, so integer counts > 2^24 silently lost exactness.
    Integer inputs must ride an integer collective path."""

    def _patched(self, monkeypatch, world=2):
        import paddle_tpu.distributed.env as env
        import paddle_tpu.distributed.collective as C
        monkeypatch.setattr(env, "get_world_size", lambda group=None: world)
        seen = {}

        def fake_all_reduce(t, op=C.ReduceOp.SUM, group=None,
                            sync_op=True):
            # simulate a 2-rank SUM of identical contributions; record
            # the dtype that actually crossed the collective
            seen["dtype"] = np.asarray(t._value).dtype
            if op == C.ReduceOp.SUM:
                t._value = t._value * world
            return t
        monkeypatch.setattr(C, "all_reduce", fake_all_reduce)
        return seen

    def test_large_int_count_stays_exact(self, monkeypatch):
        from paddle_tpu.distributed.fleet.ps_compat import UtilBase
        seen = self._patched(monkeypatch)
        big = np.array([2**24 + 1], np.int64)   # not f32-representable
        out = UtilBase().all_reduce(big, mode="sum")
        assert seen["dtype"].kind in "iu", seen
        assert out.dtype.kind in "iu"
        np.testing.assert_array_equal(out, np.array([2 * (2**24 + 1)]))

    def test_int32_sum_widens_instead_of_wrapping(self, monkeypatch):
        # per-rank counts that fit int32 must not wrap in the
        # cross-rank sum: the collective runs in int64
        from paddle_tpu.distributed.fleet.ps_compat import UtilBase
        seen = self._patched(monkeypatch)
        out = UtilBase().all_reduce(np.array([1_500_000_000], np.int32),
                                    mode="sum")
        assert seen["dtype"] == np.int64
        np.testing.assert_array_equal(out, np.array([3_000_000_000]))
        assert out.dtype == np.int64            # too big to narrow back

    def test_unsigned_rides_unsigned(self, monkeypatch):
        # uint inputs widen to uint64, not int64 (2^63 would wrap)
        from paddle_tpu.distributed.fleet.ps_compat import UtilBase
        seen = self._patched(monkeypatch)
        out = UtilBase().all_reduce(
            np.array([2_000_000_000], np.uint32), mode="sum")
        assert seen["dtype"] == np.uint64
        np.testing.assert_array_equal(out, np.array([4_000_000_000]))

    def test_int_mean_rides_exact_integer_sum(self, monkeypatch):
        # REVIEW: integer mean fell through to the float32 AVG
        # collective; it must cross the wire as an exact integer SUM
        # and divide by world size on the host (result is float)
        from paddle_tpu.distributed.fleet.ps_compat import UtilBase
        seen = self._patched(monkeypatch)
        big = np.array([2**24 + 1], np.int64)   # not f32-representable
        out = UtilBase().all_reduce(big, mode="mean")
        assert seen["dtype"].kind in "iu", seen
        assert out.dtype == np.float64
        np.testing.assert_array_equal(out, np.array([float(2**24 + 1)]))

    def test_float_path_unchanged(self, monkeypatch):
        from paddle_tpu.distributed.fleet.ps_compat import UtilBase
        seen = self._patched(monkeypatch)
        out = UtilBase().all_reduce(np.array([1.5], np.float64),
                                    mode="sum")
        assert seen["dtype"] == np.float32
        np.testing.assert_allclose(out, [3.0])

    def test_single_process_passthrough_preserves_dtype(self):
        from paddle_tpu.distributed.fleet.ps_compat import UtilBase
        big = np.array([2**53 + 1], np.int64)
        out = UtilBase().all_reduce(big, mode="sum")
        np.testing.assert_array_equal(out, big)
        assert out.dtype == np.int64
        # integer mean returns float even at world 1 (same contract as
        # the multi-rank path)
        mean = UtilBase().all_reduce(np.array([7], np.int64), mode="mean")
        assert mean.dtype == np.float64
        np.testing.assert_array_equal(mean, [7.0])


class TestControllerEpochNamespacedLiveness:
    """ADVICE r5: exit/heartbeat markers persisted across elastic
    re-ranks, so a stale ``exit/N == 0`` from a prior incarnation could
    mask a genuinely dead node after ranks were re-assigned. Liveness
    keys are now namespaced by the coordination epoch."""

    class _FakeStore:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = str(v)

        def get(self, k):
            return self.d.get(k)

    def _controller(self, epoch):
        import time
        from paddle_tpu.distributed.launch.controller import (Controller,
                                                              JobSpec)
        c = Controller(JobSpec(script="x", nnodes=2, node_rank=0))
        c.store = self._FakeStore()
        c._coord_epoch = epoch
        return c, time.time()

    def test_stale_exit_from_prior_epoch_does_not_mask_failure(self):
        c, now = self._controller(epoch=5)
        c.store.set("heartbeat/5/1", str(now - 1000))   # stale peer
        c.store.set("exit/0/1", "0")     # clean exit of a PRIOR epoch
        assert c._peer_failure() == 1    # still a failure now

    def test_current_epoch_clean_exit_not_a_failure(self):
        c, now = self._controller(epoch=5)
        c.store.set("heartbeat/5/1", str(now - 1000))
        c.store.set("exit/5/1", "0")     # clean exit, THIS incarnation
        assert c._peer_failure() is None

    def test_heartbeat_written_under_epoch_key(self):
        c, _ = self._controller(epoch=7)
        c._heartbeat()
        assert "heartbeat/7/0" in c.store.d

    def test_dead_before_first_heartbeat_detected_after_grace(self):
        # a peer that dies before its first beat of a NEW epoch leaves
        # no key under that epoch; after the grace window it must still
        # count as failed (its old-epoch keys are ignored by design)
        c, now = self._controller(epoch=5)
        c._watch_start = now - 1000
        assert c._peer_failure() == 1

    def test_missing_heartbeat_within_grace_tolerated(self):
        c, now = self._controller(epoch=5)
        c._watch_start = now
        assert c._peer_failure() is None
