"""End-to-end model training (BASELINE config 1 slice: ResNet on one device;
reference analog: test/legacy_test model-level tests)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _tiny_batch(n=8, c=10, hw=32):
    paddle.seed(3)
    x = paddle.randn([n, 3, hw, hw])
    y = paddle.to_tensor(np.random.randint(0, c, n))
    return x, y


class TestResNetE2E:
    def test_resnet18_forward_shapes(self):
        from paddle_tpu.vision.models import resnet18
        net = resnet18(num_classes=10)
        net.eval()
        out = net(paddle.randn([2, 3, 64, 64]))
        assert out.shape == [2, 10]

    def test_resnet_train_step_eager(self):
        from paddle_tpu.vision.models import ResNet, BasicBlock
        net = ResNet(BasicBlock, 18, num_classes=10)
        net.train()
        opt = paddle.optimizer.Momentum(0.05,
                                        parameters=net.parameters())
        x, y = _tiny_batch()
        losses = []
        for _ in range(4):
            loss = F.cross_entropy(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.item()))
        assert losses[-1] < losses[0]

    def test_resnet_train_step_compiled(self):
        from paddle_tpu.vision.models import ResNet, BasicBlock
        net = ResNet(BasicBlock, 18, num_classes=10)
        net.train()
        compiled = paddle.jit.to_static(net)
        opt = paddle.optimizer.Momentum(0.05, parameters=net.parameters())
        x, y = _tiny_batch()
        losses = []
        for _ in range(4):
            loss = F.cross_entropy(compiled(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.item()))
        assert losses[-1] < losses[0]

    def test_lenet_mnist_pipeline(self):
        from paddle_tpu.vision.models import LeNet
        from paddle_tpu.vision.datasets import FakeData
        from paddle_tpu.io import DataLoader
        net = LeNet()
        opt = paddle.optimizer.Adam(0.001, parameters=net.parameters())
        ds = FakeData(size=16, image_shape=(1, 28, 28), num_classes=10)
        loader = DataLoader(ds, batch_size=8)
        for img, label in loader:
            loss = F.cross_entropy(net(img), label)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert np.isfinite(float(loss.item()))

    def test_hapi_model_fit(self):
        from paddle_tpu.vision.models import LeNet
        from paddle_tpu.vision.datasets import FakeData
        from paddle_tpu.hapi import Model
        from paddle_tpu.metric import Accuracy
        net = LeNet()
        model = Model(net)
        model.prepare(
            optimizer=paddle.optimizer.Adam(0.001,
                                            parameters=net.parameters()),
            loss=nn.CrossEntropyLoss(), metrics=[Accuracy()])
        ds = FakeData(size=16, image_shape=(1, 28, 28), num_classes=10)
        model.fit(ds, batch_size=8, epochs=1, verbose=0)
        res = model.evaluate(ds, batch_size=8, verbose=0)
        assert "loss" in res

    def test_amp_training(self):
        from paddle_tpu.vision.models import LeNet
        net = LeNet()
        opt = paddle.optimizer.Adam(0.001, parameters=net.parameters())
        scaler = paddle.amp.GradScaler()
        x = paddle.randn([4, 1, 28, 28])
        y = paddle.to_tensor(np.random.randint(0, 10, 4))
        with paddle.amp.auto_cast(level="O1"):
            loss = F.cross_entropy(net(x), y)
        scaler.scale(loss).backward()
        scaler.step(opt)
        scaler.update()
        assert np.isfinite(float(loss.item()))
