"""paddle.fft / paddle.signal / paddle.distribution / regularizer / batch
parity tests (reference: test/legacy_test/test_fft.py, test_signal.py,
test/distribution/, test_regularizer.py)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import fft, signal
from paddle_tpu import distribution as dist
from paddle_tpu.regularizer import L1Decay, L2Decay


class TestFFT:
    def test_1d_family_matches_numpy(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 16).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(np.asarray(fft.fft(t).numpy()),
                                   np.fft.fft(x), atol=1e-4)
        np.testing.assert_allclose(np.asarray(fft.ifft(t).numpy()),
                                   np.fft.ifft(x), atol=1e-4)
        np.testing.assert_allclose(np.asarray(fft.rfft(t).numpy()),
                                   np.fft.rfft(x), atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(fft.irfft(fft.rfft(t)).numpy()), x, atol=1e-4)
        np.testing.assert_allclose(np.asarray(fft.ihfft(t).numpy()),
                                   np.fft.ihfft(x), atol=1e-4)

    def test_nd_and_norms(self):
        rng = np.random.RandomState(1)
        x = rng.randn(3, 8, 8).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(np.asarray(fft.fft2(t).numpy()),
                                   np.fft.fft2(x), atol=1e-3)
        np.testing.assert_allclose(
            np.asarray(fft.fftn(t, norm="ortho").numpy()),
            np.fft.fftn(x, norm="ortho"), atol=1e-3)
        np.testing.assert_allclose(
            np.asarray(fft.ifftn(fft.fftn(t).numpy()).numpy()), x,
            atol=1e-4)
        with pytest.raises(ValueError):
            fft.fft(t, norm="bogus")

    def test_freq_shift_helpers(self):
        np.testing.assert_allclose(
            np.asarray(fft.fftfreq(10, d=0.5).numpy()),
            np.fft.fftfreq(10, d=0.5).astype(np.float32), atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(fft.rfftfreq(10).numpy()),
            np.fft.rfftfreq(10).astype(np.float32), atol=1e-6)
        x = np.arange(8, dtype=np.float32)
        np.testing.assert_allclose(
            np.asarray(fft.ifftshift(fft.fftshift(
                paddle.to_tensor(x)).numpy()).numpy()), x)

    def test_grad_flows(self):
        x = paddle.to_tensor(np.random.randn(16).astype(np.float32))
        x.stop_gradient = False
        y = fft.rfft(x)
        loss = (y.abs() ** 2).sum()
        loss.backward()
        assert x.grad is not None
        # Parseval: d/dx sum|X|^2 = 2*N_effective*x-ish — just finite
        assert np.isfinite(np.asarray(x.grad.numpy())).all()


class TestSignal:
    def test_frame_overlap_add_inverse_for_non_overlap(self):
        rng = np.random.RandomState(2)
        x = rng.randn(3, 256).astype(np.float32)
        fr = signal.frame(paddle.to_tensor(x), 32, 32)  # no overlap
        assert np.asarray(fr.numpy()).shape == (3, 32, 8)
        back = signal.overlap_add(fr, 32)
        np.testing.assert_allclose(np.asarray(back.numpy()), x, atol=1e-5)

    def test_stft_istft_roundtrip(self):
        rng = np.random.RandomState(3)
        x = rng.randn(2, 400).astype(np.float32)
        win = paddle.to_tensor(np.hanning(128).astype(np.float32))
        S = signal.stft(paddle.to_tensor(x), n_fft=128, hop_length=32,
                        window=win)
        assert np.asarray(S.numpy()).shape[1] == 65  # onesided bins
        back = signal.istft(S, n_fft=128, hop_length=32, window=win,
                            length=400)
        err = np.abs(np.asarray(back.numpy()) - x)[:, 64:-80].max()
        assert err < 1e-3

    def test_stft_matches_manual_dft(self):
        rng = np.random.RandomState(4)
        x = rng.randn(200).astype(np.float32)
        S = np.asarray(signal.stft(paddle.to_tensor(x), n_fft=64,
                                   hop_length=64, center=False).numpy())
        # frame 0 is x[0:64]
        ref = np.fft.rfft(x[:64])
        np.testing.assert_allclose(S[:, 0], ref, atol=1e-3)


class TestDistributions:
    def test_normal_moments_logprob_kl(self):
        n1, n2 = dist.Normal(0.0, 1.0), dist.Normal(1.0, 2.0)
        s = np.asarray(n1.sample((20000,)).numpy())
        assert abs(s.mean()) < 0.05 and abs(s.std() - 1) < 0.05
        lp = float(np.asarray(n1.log_prob(paddle.to_tensor(0.0)).numpy()))
        assert abs(lp - (-0.5 * np.log(2 * np.pi))) < 1e-5
        kl = float(np.asarray(dist.kl_divergence(n1, n2).numpy()))
        assert abs(kl - (np.log(2) + 2 / 8 - 0.5)) < 1e-5

    def test_categorical_and_bernoulli(self):
        # probs/log_prob normalize linearly (reference
        # categorical.py:148-149): weights [2,3,5] -> p = [.2,.3,.5]
        c = dist.Categorical(np.array([2.0, 3.0, 5.0], np.float32))
        lp = np.asarray(c.log_prob(paddle.to_tensor(np.array([2]))).numpy())
        assert abs(np.exp(lp[0]) - 0.5) < 1e-5
        pr = np.asarray(c.probs(paddle.to_tensor(np.array([0, 1]))).numpy())
        np.testing.assert_allclose(pr, [0.2, 0.3], atol=1e-6)
        # entropy/sample go through softmax (reference _logits_to_probs)
        c2 = dist.Categorical(np.log(np.array([0.2, 0.3, 0.5], np.float32)))
        ent = float(np.asarray(c2.entropy().numpy()))
        ref = -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5))
        assert abs(ent - ref) < 1e-5
        b = dist.Bernoulli(np.array(0.25, np.float32))
        s = np.asarray(b.sample((20000,)).numpy())
        assert abs(s.mean() - 0.25) < 0.02

    @pytest.mark.slow
    def test_gamma_beta_dirichlet(self):
        g = dist.Gamma(2.0, 0.5)
        gs = np.asarray(g.sample((20000,)).numpy())
        assert abs(gs.mean() - 4.0) < 0.2
        b = dist.Beta(2.0, 3.0)
        assert abs(float(np.asarray(b.mean)) - 0.4) < 1e-6
        d = dist.Dirichlet(np.array([1.0, 2.0, 3.0], np.float32))
        ds = np.asarray(d.sample((5000,)).numpy())
        np.testing.assert_allclose(ds.mean(0), [1 / 6, 2 / 6, 3 / 6],
                                   atol=0.03)
        # KL(p, p) == 0
        assert abs(float(np.asarray(
            dist.kl_divergence(d, d).numpy()))) < 1e-5

    def test_lognormal_laplace_gumbel(self):
        ln = dist.LogNormal(0.0, 0.5)
        ls = np.asarray(ln.sample((20000,)).numpy())
        assert abs(ls.mean() - np.exp(0.125)) < 0.05
        la = dist.Laplace(1.0, 2.0)
        assert abs(float(np.asarray(la.variance)) - 8.0) < 1e-5
        gu = dist.Gumbel(0.0, 1.0)
        gs = np.asarray(gu.sample((20000,)).numpy())
        assert abs(gs.mean() - np.euler_gamma) < 0.05

    def test_independent_and_transformed(self):
        base = dist.Normal(np.zeros((3, 4), np.float32),
                           np.ones((3, 4), np.float32))
        ind = dist.Independent(base, 1)
        assert ind.event_shape == (4,) and ind.batch_shape == (3,)
        lp = np.asarray(ind.log_prob(
            paddle.to_tensor(np.zeros((3, 4), np.float32))).numpy())
        assert lp.shape == (3,)

        class Exp:
            def forward(self, x):
                return paddle.to_tensor(jnp.exp(np.asarray(x.numpy())))

            def inverse(self, y):
                return paddle.to_tensor(jnp.log(np.asarray(y.numpy())))

            def forward_log_det_jacobian(self, x):
                return paddle.to_tensor(np.asarray(x.numpy()))

        td = dist.TransformedDistribution(dist.Normal(0.0, 1.0), [Exp()])
        # matches LogNormal log_prob
        v = paddle.to_tensor(np.array(1.7, np.float32))
        np.testing.assert_allclose(
            np.asarray(td.log_prob(v).numpy()),
            np.asarray(dist.LogNormal(0.0, 1.0).log_prob(v).numpy()),
            atol=1e-5)

    def test_kl_unregistered_raises(self):
        with pytest.raises(NotImplementedError):
            dist.kl_divergence(dist.Normal(0.0, 1.0),
                               dist.Gamma(1.0, 1.0))

    def test_kl_most_specific_dispatch(self):
        """A subclass handler registered AFTER the parent pair must win
        (reference kl.py dispatches most-specific, not insertion order)."""
        from paddle_tpu.distribution import register_kl, _KL_REGISTRY

        class _MyNormal(dist.Normal):
            pass

        @register_kl(_MyNormal, dist.Normal)
        def _kl_mynormal(p, q):  # noqa: ARG001
            return "subclass-handler"

        try:
            p = _MyNormal(0.0, 1.0)
            q = dist.Normal(1.0, 2.0)
            assert dist.kl_divergence(p, q) == "subclass-handler"
            # plain Normal pair still routes to the generic handler
            got = dist.kl_divergence(dist.Normal(0.0, 1.0), q)
            assert got != "subclass-handler"
        finally:
            _KL_REGISTRY.pop((_MyNormal, dist.Normal), None)


class TestRegularizerAndBatch:
    def test_l1_decay_folds_into_sgd_step(self):
        net = paddle.nn.Linear(
            4, 4, weight_attr=paddle.ParamAttr(regularizer=L1Decay(0.1)))
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        loss = (net(x) ** 2).mean()
        loss.backward()
        w0 = np.asarray(net.weight.numpy()).copy()
        g = np.asarray(net.weight.grad.numpy())
        opt.step()
        np.testing.assert_allclose(
            np.asarray(net.weight.numpy()),
            w0 - 0.1 * (g + 0.1 * np.sign(w0)), atol=1e-5)

    def test_l2_decay_acts_as_coupled_decay(self):
        net = paddle.nn.Linear(
            4, 4, weight_attr=paddle.ParamAttr(regularizer=L2Decay(0.05)))
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        loss = (net(x) ** 2).mean()
        loss.backward()
        w0 = np.asarray(net.weight.numpy()).copy()
        g = np.asarray(net.weight.grad.numpy())
        opt.step()
        np.testing.assert_allclose(
            np.asarray(net.weight.numpy()),
            w0 - 0.1 * (g + 0.05 * w0), atol=1e-5)

    def test_batch_reader(self):
        def reader():
            return iter(range(10))
        b = paddle.batch(lambda: iter(range(10)), 3)
        out = list(b())
        assert out == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
        b2 = paddle.batch(lambda: iter(range(10)), 3, drop_last=True)
        assert list(b2()) == [[0, 1, 2], [3, 4, 5], [6, 7, 8]]


class TestRegularizerPaths:
    def test_optimizer_level_l1_applies(self):
        net = paddle.nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters(),
                                   weight_decay=L1Decay(0.1))
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        loss = (net(x) ** 2).mean()
        loss.backward()
        w0 = np.asarray(net.weight.numpy()).copy()
        g = np.asarray(net.weight.grad.numpy())
        opt.step()
        np.testing.assert_allclose(
            np.asarray(net.weight.numpy()),
            w0 - 0.1 * (g + 0.1 * np.sign(w0)), atol=1e-5)

    def test_train_step_l1_matches_eager(self):
        xs = paddle.to_tensor(np.ones((2, 4), np.float32))
        ys = paddle.to_tensor(np.zeros((2, 1), np.float32))

        paddle.seed(1)
        net_f = paddle.nn.Linear(
            4, 1, weight_attr=paddle.ParamAttr(regularizer=L1Decay(0.1)))
        opt_f = paddle.optimizer.SGD(0.1, parameters=net_f.parameters())
        ts = paddle.jit.train_step(net_f,
                                   lambda o, y: ((o - y) ** 2).mean(),
                                   opt_f)
        ts(xs, ys)

        paddle.seed(1)
        net_e = paddle.nn.Linear(
            4, 1, weight_attr=paddle.ParamAttr(regularizer=L1Decay(0.1)))
        opt_e = paddle.optimizer.SGD(0.1, parameters=net_e.parameters())
        loss = ((net_e(xs) - ys) ** 2).mean()
        loss.backward()
        opt_e.step()
        np.testing.assert_allclose(np.asarray(net_f.weight.numpy()),
                                   np.asarray(net_e.weight.numpy()),
                                   atol=1e-5)


def test_program_replay_sees_inplace_weight_updates():
    from paddle_tpu import static

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        y = static.nn.fc(x, 2, bias_attr=False)
    exe = static.Executor()
    feed = {"x": np.ones((1, 4), np.float32)}
    a = exe.run(main, feed=feed, fetch_list=[y])[0]
    wt = next(iter(main._externals.values()))
    wt._value = wt._value * 0.0
    b = exe.run(main, feed=feed, fetch_list=[y])[0]
    assert not np.allclose(a, 0) and np.allclose(b, 0)


def test_static_fc_rejects_dynamic_feature_dim():
    """ADVICE round-2: a None feature dim would silently size the weight
    off the placeholder's stand-in 1 — must raise at build time."""
    import pytest
    from paddle_tpu import static

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, None, 4], "float32")
        with pytest.raises(ValueError, match="dynamic"):
            static.nn.fc(x, 2)  # feature dims = shape[1:] = (None, 4)
        # batch-only dynamism stays fine
        x2 = static.data("x2", [None, 4], "float32")
        y = static.nn.fc(x2, 2, bias_attr=False)
    assert y is not None
