"""ServingFleet (inference/fleet.py) + the host-RAM KV offload tier
(prefix_cache.py spill/restore).

The acceptance bar (ISSUE 12): a fleet of N >= 2 replicas of MIXED
engine kinds (colocated + disaggregated) behind the prefix-aware
router serves a 30-request mixed-arrival greedy stream bit-identical
to a single colocated engine, with zero steady-state retraces; routing
is deterministically prefix-affine with least-loaded fallback and
per-replica admission backpressure; and a prefix hit on a SPILLED page
restores bit-identical KV bytes (refcount + conservation invariants
held throughout)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.models import llama
from paddle_tpu.inference import (DisaggregatedEngine, GenerationConfig,
                                  ServingEngine, ServingFleet, generate)

pytestmark = pytest.mark.fleet

CFG = llama.LlamaConfig(vocab_size=97, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=4,
                        max_position_embeddings=160,
                        dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _engine(params, **kw):
    kw.setdefault("capacity", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("max_seq_len", 64)
    return ServingEngine(params, CFG, **kw)


def _disagg(params, **kw):
    kw.setdefault("prefill_devices", jax.devices()[:1])
    kw.setdefault("decode_devices", jax.devices()[1:2])
    kw.setdefault("capacity", 2)
    kw.setdefault("prefill_slots", 1)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("max_seq_len", 64)
    return DisaggregatedEngine(params, CFG, **kw)


def _want(params, p, g):
    return np.asarray(generate(params, jnp.asarray(p)[None], CFG,
                               g))[0, len(p):].tolist()


def _stream(fleet_or_eng, n=30, seed=7, max_new=5):
    """n greedy requests arriving in waves interleaved with steps."""
    rng = np.random.RandomState(seed)
    sizes = rng.randint(4, 15, n)
    reqs = []
    for i, s in enumerate(sizes):
        reqs.append(fleet_or_eng.submit(
            rng.randint(0, 97, (int(s),)).astype(np.int32),
            GenerationConfig(max_new_tokens=max_new, greedy=True)))
        if i % 3 == 2:
            fleet_or_eng.step()
            fleet_or_eng.step()
    fleet_or_eng.drain()
    return [r.output_ids for r in reqs]


def _same(a, b):
    return all(np.array_equal(x, y) for x, y in zip(a, b))


@pytest.fixture(scope="module")
def ref_stream(params):
    return _stream(_engine(params, capacity=3))


# -- the acceptance stream: mixed-kind bit-parity + zero retraces ------

def test_fleet_bit_parity_mixed_engine_kinds(params, ref_stream):
    """Fleet of a colocated prefix-cached replica + a disaggregated
    replica: the 30-request stream is bit-identical to the single
    colocated engine, a warm repeat of the same stream stays
    bit-identical with ZERO steady-state retraces, and both replicas
    actually served work."""
    fleet = ServingFleet(
        [("coloc", _engine(params, prefix_cache=True,
                           observability=True)),
         ("disagg", _disagg(params, prefix_cache=True,
                            observability=True))],
        observability=True)
    cold = _stream(fleet)
    assert _same(ref_stream, cold), "fleet greedy output diverged"
    m = fleet.metrics()
    per = m["routing"]["per_replica"]
    assert per["coloc"]["routed"] > 0 and per["disagg"]["routed"] > 0
    assert m["requests_completed"] == 30
    assert m["latency"]["ttft_ms"]["count"] == 30  # shared histograms
    fleet.reset_metrics()            # arms every replica's watchdog
    warm = _stream(fleet)            # same seed -> same prompts
    assert _same(ref_stream, warm), "warm fleet stream diverged"
    m = fleet.metrics()
    assert m["retrace_warnings"] == 0
    # warm repeats route onto the replica already holding the prefix
    assert m["routing"]["warm"] > 0
    assert m["replicas"]["coloc"]["decode_traces"] == 1
    assert m["replicas"]["disagg"]["groups"]["decode"][
        "decode_traces"] == 1


# -- routing ----------------------------------------------------------

def test_prefix_affinity_routing_deterministic(params):
    """Cold placement spreads by least-loaded round-robin; warm
    requests land deterministically on the replica that already holds
    their prefix pages."""
    rng = np.random.RandomState(1)
    fleet = ServingFleet([_engine(params, prefix_cache=True),
                          _engine(params, prefix_cache=True)])
    g = GenerationConfig(max_new_tokens=3, greedy=True)
    a = rng.randint(0, 97, (12,)).astype(np.int32)
    b = rng.randint(0, 97, (12,)).astype(np.int32)
    ra = fleet.submit(a, g)          # cold -> replica0 (rr tie-break)
    rb = fleet.submit(b, g)          # cold -> replica1
    fleet.drain()
    per = fleet.metrics()["routing"]["per_replica"]
    assert per["replica0"]["routed"] == 1
    assert per["replica1"]["routed"] == 1
    a2 = np.concatenate([a, rng.randint(0, 97, (4,))]).astype(np.int32)
    b2 = np.concatenate([b, rng.randint(0, 97, (4,))]).astype(np.int32)
    ra2 = fleet.submit(a2, g)        # warm -> replica0
    rb2 = fleet.submit(b2, g)        # warm -> replica1
    fleet.drain()
    m = fleet.metrics()
    assert m["routing"]["warm"] == 2
    assert m["routing"]["warm_hit_ratio"] == 0.5
    per = m["routing"]["per_replica"]
    assert per["replica0"]["warm_routed"] == 1
    assert per["replica1"]["warm_routed"] == 1
    # the replicas' caches confirm the affinity (one hit each)
    assert m["replicas"]["replica0"]["prefix_cache"]["hits"] == 1
    assert m["replicas"]["replica1"]["prefix_cache"]["hits"] == 1
    for req, p in ((ra, a), (rb, b), (ra2, a2), (rb2, b2)):
        assert req.tokens == _want(params, p, g)


def test_round_robin_and_least_loaded_policies(params):
    rng = np.random.RandomState(2)
    g = GenerationConfig(max_new_tokens=2, greedy=True)
    prompts = [rng.randint(0, 97, (6,)).astype(np.int32)
               for _ in range(4)]
    rr = ServingFleet([_engine(params), _engine(params)],
                      policy="round_robin")
    for p in prompts:
        rr.submit(p, g)
    per = rr.metrics()["routing"]["per_replica"]
    assert per["replica0"]["routed"] == 2
    assert per["replica1"]["routed"] == 2
    assert rr.metrics()["routing"]["warm_hit_ratio"] == 0.0
    rr.drain()
    ll = ServingFleet([_engine(params), _engine(params)],
                      policy="least_loaded")
    ll.submit(prompts[0], g)         # replica0 now loaded
    r1 = ll._replicas[1]
    ll.submit(prompts[1], g)         # least loaded -> replica1
    assert r1.routed == 1
    ll.drain()


def test_backpressure_diverts_warm_request_from_saturated_replica(
        params):
    """Per-replica admission backpressure: a warm request whose home
    replica's queue is at max_queue_depth diverts to a cold replica
    (counted) instead of queueing behind it — and still completes
    bit-exactly there."""
    rng = np.random.RandomState(3)
    eng0 = _engine(params, capacity=1, prefix_cache=True)
    eng1 = _engine(params, capacity=1, prefix_cache=True)
    fleet = ServingFleet([eng0, eng1], max_queue_depth=1)
    g = GenerationConfig(max_new_tokens=4, greedy=True)
    a = rng.randint(0, 97, (12,)).astype(np.int32)
    fleet.submit(a, g)               # cold -> replica0, caches a
    fleet.drain()
    # saturate replica0's admission queue (submitted, never stepped)
    eng0.submit(rng.randint(0, 97, (8,)).astype(np.int32), g)
    eng0.submit(rng.randint(0, 97, (8,)).astype(np.int32), g)
    assert eng0.queue_depth >= 1
    a2 = np.concatenate([a, rng.randint(0, 97, (4,))]).astype(np.int32)
    r = fleet.submit(a2, g)          # warm home saturated -> divert
    m = fleet.metrics()
    assert m["routing"]["diverted"] == 1
    assert m["routing"]["per_replica"]["replica1"]["routed"] == 1
    fleet.drain()
    assert r.tokens == _want(params, a2, g)


def test_divert_prefers_shorter_warm_match_over_cold(params):
    """REVIEW fix: when the best-match replica is saturated, an OPEN
    replica holding a shorter warm match of the same prompt beats cold
    placement (a partial prefix skip beats a full cold prefill)."""
    rng = np.random.RandomState(7)
    eng0 = _engine(params, capacity=1, prefix_cache=True)
    eng1 = _engine(params, capacity=1, prefix_cache=True)
    eng2 = _engine(params, capacity=1, prefix_cache=True)
    fleet = ServingFleet([eng0, eng1, eng2], max_queue_depth=1)
    g = GenerationConfig(max_new_tokens=3, greedy=True)
    a = rng.randint(0, 97, (12,)).astype(np.int32)
    fleet.submit(a, g)               # full prompt cached on replica0
    fleet.drain()
    eng1.submit(a[:8], g)            # a SHORTER prefix on replica1
    eng1.drain()
    eng0.submit(rng.randint(0, 97, (8,)).astype(np.int32), g)
    eng0.submit(rng.randint(0, 97, (8,)).astype(np.int32), g)
    assert eng0.queue_depth >= 1     # best-match home saturated
    r = fleet.submit(np.concatenate([a, rng.randint(0, 97, (4,))])
                     .astype(np.int32), g)
    m = fleet.metrics()
    assert m["routing"]["diverted"] == 1
    assert m["routing"]["warm"] == 1          # the divert stayed warm
    assert m["routing"]["per_replica"]["replica1"]["routed"] == 1
    fleet.drain()
    assert r.done
    assert eng1.metrics()["prefix_cache"]["hits"] >= 1


def test_fleet_validation(params):
    eng = _engine(params)
    with pytest.raises(ValueError, match="at least one"):
        ServingFleet([])
    with pytest.raises(ValueError, match="duplicate"):
        ServingFleet([("a", eng), ("a", _engine(params))])
    with pytest.raises(ValueError, match="twice"):
        ServingFleet([eng, eng])
    with pytest.raises(ValueError, match="policy"):
        ServingFleet([eng], policy="random")
    with pytest.raises(ValueError, match="max_queue_depth"):
        ServingFleet([eng], max_queue_depth=0)


# -- host-RAM KV offload tier -----------------------------------------

def test_spill_restore_byte_identity_and_refcounts(params):
    """The acceptance bullet: spill a cached prefix to host RAM, hit
    it again — the restored pages hold BIT-identical KV bytes, outputs
    match generate() exactly, and the refcount/conservation invariants
    hold through the whole spill/restore cycle."""
    rng = np.random.RandomState(4)
    eng = _engine(params, prefix_cache=True, kv_offload=True)
    g = GenerationConfig(max_new_tokens=4, greedy=True)
    p = rng.randint(0, 97, (12,)).astype(np.int32)
    r1 = eng.submit(p, g)
    eng.drain()
    assert r1.tokens == _want(params, p, g)
    pc = eng._pcache
    full, _, _ = pc.match(p)
    assert len(full) == 3            # 12 tokens = 3 full pages
    before = [(np.asarray(eng._k_pools[:, nd.page]),
               np.asarray(eng._v_pools[:, nd.page])) for nd in full]
    # force the whole tree out to the host tier
    spilled = pc.evict(100)
    assert spilled >= 3
    assert all(nd.page is None and nd.host is not None for nd in full)
    st = pc.stats
    assert st["spilled_pages"] == spilled
    assert pc.host_pages == spilled
    assert pc.cached_pages == 0
    assert eng.counters["kv_spill_bytes"] > 0
    # every spilled page went back to the allocator
    assert len(eng.mgr.free) + 1 == eng.num_blocks
    # warm hit on the spilled prefix: acquire restores, output exact
    r2 = eng.submit(p, g)
    eng.drain()
    assert r2.tokens == _want(params, p, g)
    assert st["restored_pages"] >= 2         # the shared full pages
    assert st["hits"] == 1
    assert eng.counters["kv_restore_bytes"] > 0
    assert eng.counters["offload_traces"] == 2   # extract + insert
    full2, _, _ = pc.match(p)
    for nd, (kb, vb) in zip(full2[:2], before[:2]):
        assert nd.page is not None
        np.testing.assert_array_equal(
            np.asarray(eng._k_pools[:, nd.page]), kb)
        np.testing.assert_array_equal(
            np.asarray(eng._v_pools[:, nd.page]), vb)
    rc = eng.mgr.refcount
    assert (rc >= 0).all()
    assert all(rc[pg] == 0 for pg in eng.mgr.free)
    m = eng.metrics()["prefix_cache"]
    assert (len(eng.mgr.free) + m["cached_pages"] + 1
            == eng.num_blocks)


def test_eviction_pressure_spills_then_serves_warm_from_host(params):
    """An undersized pool under a multi-prompt stream spills instead
    of destroying warm state: every output stays exact, and a repeat
    of the FIRST (long-evicted) prompt is served warm out of the host
    tier — the capacity-extension proof (HBM + host RAM)."""
    rng = np.random.RandomState(5)
    eng = _engine(params, capacity=2, num_blocks=14, max_seq_len=32,
                  prefix_cache=True, kv_offload=True,
                  observability=True)
    g = GenerationConfig(max_new_tokens=4, greedy=True)
    reqs = [(pp := rng.randint(0, 97, (16,)).astype(np.int32),
             eng.submit(pp, g)) for _ in range(6)]
    eng.drain()
    for pp, r in reqs:
        assert r.tokens == _want(params, pp, g)
    m = eng.metrics()["prefix_cache"]
    assert m["spilled_pages"] > 0
    assert m["evicted_pages"] == 0           # nothing was destroyed
    hits0 = m["hits"]
    first = reqs[0][0]
    r = eng.submit(first, g)
    eng.drain()
    assert r.tokens == _want(params, first, g)
    m = eng.metrics()["prefix_cache"]
    assert m["hits"] == hits0 + 1
    assert m["restored_pages"] > 0           # served from the host tier
    # spill/restore distributions joined the latency report
    lat = eng.metrics()["latency"]
    assert lat["spill_ms"]["count"] == m["spilled_pages"]
    assert lat["restore_ms"]["count"] == m["restored_pages"]
    rc = eng.mgr.refcount
    assert (rc >= 0).all()
    assert all(rc[pg] == 0 for pg in eng.mgr.free)
    assert (len(eng.mgr.free) + m["cached_pages"] + 1
            == eng.num_blocks)


def test_host_budget_drops_lru_spilled_pages(params):
    """kv_offload=<int> bounds the host tier: past the budget the LRU
    childless spilled node dies for real (counted), and the tier never
    exceeds the cap."""
    rng = np.random.RandomState(6)
    eng = _engine(params, capacity=2, num_blocks=14, max_seq_len=32,
                  prefix_cache=True, kv_offload=2)
    g = GenerationConfig(max_new_tokens=4, greedy=True)
    for _ in range(6):
        eng.submit(rng.randint(0, 97, (16,)).astype(np.int32), g)
    eng.drain()
    m = eng.metrics()["prefix_cache"]
    assert m["spilled_pages"] > 2
    assert m["host_evicted_pages"] > 0
    assert m["host_pages"] <= 2


def test_offload_requires_prefix_cache(params):
    with pytest.raises(ValueError, match="prefix_cache"):
        _engine(params, kv_offload=True)


def test_fleet_offload_aggregation(params):
    """The fleet's offload report sums every replica's host tier."""
    rng = np.random.RandomState(8)
    fleet = ServingFleet(
        [_engine(params, capacity=2, num_blocks=14, max_seq_len=32,
                 prefix_cache=True, kv_offload=True)
         for _ in range(2)])
    g = GenerationConfig(max_new_tokens=4, greedy=True)
    for _ in range(8):
        fleet.submit(rng.randint(0, 97, (16,)).astype(np.int32), g)
    fleet.drain()
    off = fleet.metrics()["offload"]
    assert off["spilled_pages"] > 0
    assert off["spill_bytes"] > 0
    per_replica = [r.engine.offload_metrics()["spilled_pages"]
                   for r in fleet._replicas]
    assert off["spilled_pages"] == sum(per_replica)


# -- metrics schema ----------------------------------------------------

FLEET_BASE_KEYS = {
    "replicas_n", "requests_submitted", "requests_completed",
    "tokens_generated", "tokens_per_sec", "wall_time_s", "fleet_steps",
    "drain_truncations", "ttft_ms_mean", "ttft_ms_max", "routing",
    "offload", "replicas",
    # r21: per-replica roofline observatory reports
    "roofline",
}
FLEET_OBS_KEYS = {"latency", "gauges", "retrace_warnings",
                  "stall_dumps", "timeline_events", "timeline_dropped"}
FLEET_LATENCY_KEYS = {"ttft_ms", "tpot_ms", "queue_wait_ms", "e2e_ms",
                      "step_ms"}
ROUTING_KEYS = {"policy", "warm", "cold", "diverted", "warm_hit_ratio",
                "per_replica"}
OFFLOAD_KEYS = {"spilled_pages", "restored_pages", "readopted_pages",
                "host_evicted_pages", "host_pages", "spill_bytes",
                "restore_bytes"}


def test_fleet_metrics_schema_frozen(params):
    """The fleet metric key set is a CONTRACT (bench output): extend
    deliberately, never by accident — enabled AND disabled."""
    from paddle_tpu.observability import TelemetryConfig
    fleet = ServingFleet([_engine(params), _engine(params)])
    _stream(fleet, n=4)
    m = fleet.metrics()
    assert set(m.keys()) == FLEET_BASE_KEYS
    assert "telemetry" not in m           # disabled = key absent (r22)
    assert set(m["routing"].keys()) == ROUTING_KEYS
    assert set(m["offload"].keys()) == OFFLOAD_KEYS
    fleet = ServingFleet(
        [_engine(params, observability=True),
         _engine(params, observability=True)], observability=True,
        telemetry=TelemetryConfig(sample_every=2, detectors=()))
    _stream(fleet, n=4)
    m = fleet.metrics()
    # telemetry (r22) adds exactly the telemetry sub-dict: the fleet
    # rollup plus every replica's series under a `replica` label
    assert set(m.keys()) == \
        FLEET_BASE_KEYS | FLEET_OBS_KEYS | {"telemetry"}
    assert set(m["telemetry"].keys()) == {"samples", "series",
                                          "alerts", "rules"}
    assert m["telemetry"]["samples"] >= 1
    tel = fleet.telemetry
    reps = {dict(s.labels).get("replica") for s in tel.series()}
    assert {"replica0", "replica1"} <= reps
    assert set(m["latency"].keys()) == FLEET_LATENCY_KEYS
    assert m["latency"]["ttft_ms"]["count"] == 4
    assert m["latency"]["tpot_ms"]["count"] == 4
    # reset restarts the window and re-shares the histograms
    fleet.reset_metrics()
    _stream(fleet, n=3, seed=9)
    m = fleet.metrics()
    assert m["latency"]["ttft_ms"]["count"] == 3
    assert m["requests_submitted"] == 3


def test_fleet_timeline_route_events(params, tmp_path):
    fleet = ServingFleet([_engine(params, prefix_cache=True),
                          _engine(params, prefix_cache=True)],
                         observability=True)
    _stream(fleet, n=4)
    path = str(tmp_path / "fleet_timeline.jsonl")
    fleet.write_timeline(path)
    import json
    with open(path) as fh:
        lines = [json.loads(ln) for ln in fh]
    header = lines[0]
    assert header.get("fleet") is True
    assert header.get("policy") == "prefix"
    routes = [ln for ln in lines
              if ln.get("name") == "route"]
    assert len(routes) == 4
    assert all("replica" in ev and "matched_tokens" in ev
               for ev in routes)
    # trace_summary's serving mode renders a fleet routing section
    # from the route events (r22 satellite)
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        from trace_summary import load, render, summarize
    finally:
        sys.path.pop(0)
    meta, events, requests = load(path)
    summary = summarize(meta, events, requests)
    rt = summary["routing"]
    assert rt["requests"] == 4
    assert rt["warm"] + rt["cold"] == 4
    assert rt["warm_hit_ratio"] == pytest.approx(rt["warm"] / 4)
    assert set(rt["per_replica"]) <= {"replica0", "replica1"}
    assert sum(d["routed"] for d in rt["per_replica"].values()) == 4
    assert "fleet routing:" in render(summary)


# -- audit wiring ------------------------------------------------------

def test_catalog_offload_specs_audit_clean():
    from paddle_tpu.analysis import audit_spec
    from paddle_tpu.analysis.catalog import (CATALOG_PROGRAMS,
                                             build_catalog)
    names = ["serving_kv_spill_extract", "serving_kv_restore_insert"]
    for n in names:
        assert n in CATALOG_PROGRAMS
    specs = build_catalog(names=names, register=False)
    assert sorted(s.name for s in specs) == sorted(names)
    for s in specs:
        rep = audit_spec(s)
        assert rep.findings == [], [f.fingerprint for f in rep.findings]
    ins = next(s for s in specs
               if s.name == "serving_kv_restore_insert")
    assert ins.donate_argnums == (0, 1)
    assert ins.carry == {0: 0, 1: 1}


def test_engine_audit_covers_offload_and_restores_counters(params):
    eng = _engine(params, prefix_cache=True, kv_offload=True)
    eng.submit(np.arange(1, 9, dtype=np.int32),
               GenerationConfig(max_new_tokens=2, greedy=True))
    eng.drain()
    before = eng.counters["offload_traces"]
    reports = eng.audit(register=False)
    assert all(r.findings == [] for r in reports)
    assert eng.counters["offload_traces"] == before
    assert {r.program for r in reports} >= {
        "serving_kv_spill_extract", "serving_kv_restore_insert"}
