"""Cached-decode paths of the fused transformer ops (reference:
python/paddle/incubate/nn/functional/fused_transformer.py generation
mode: cache_kvs/time_step/pre_caches/rotary_embs; CacheKV growth in
fused_multi_head_attention)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate.nn.functional as IF

t = paddle.to_tensor


def _mk_stack(rng, n_layers, hid, nh, ffn, dtype=np.float32):
    hd = hid // nh
    mk = lambda *s: t((rng.randn(*s) * 0.05).astype(dtype))
    return dict(
        ln_scales=[mk(hid) + 1.0 for _ in range(n_layers)],
        ln_biases=[mk(hid) for _ in range(n_layers)],
        qkv_weights=[mk(3, nh, hd, hid) for _ in range(n_layers)],
        qkv_biases=[mk(3, nh, hd) for _ in range(n_layers)],
        linear_weights=[mk(hid, hid) for _ in range(n_layers)],
        linear_biases=[mk(hid) for _ in range(n_layers)],
        ffn_ln_scales=[mk(hid) + 1.0 for _ in range(n_layers)],
        ffn_ln_biases=[mk(hid) for _ in range(n_layers)],
        ffn1_weights=[mk(hid, ffn) for _ in range(n_layers)],
        ffn1_biases=[mk(ffn) for _ in range(n_layers)],
        ffn2_weights=[mk(ffn, hid) for _ in range(n_layers)],
        ffn2_biases=[mk(hid) for _ in range(n_layers)],
    )


def _caches(b, nh, hd, m, n_layers):
    return [t(np.zeros((2, b, nh, m, hd), np.float32))
            for _ in range(n_layers)]


class TestFusedMultiTransformerCached:
    def test_prefill_plus_decode_matches_full_causal(self):
        """Prefill S tokens then greedy-decode G more, one
        time_step'ed call each; the per-position outputs must equal ONE
        non-cached causal run over the full S+G sequence."""
        rng = np.random.RandomState(0)
        B, S, G, HID, NH, FFN, L = 2, 5, 3, 16, 2, 32, 2
        HD = HID // NH
        w = _mk_stack(rng, L, HID, NH, FFN)
        x_full = (rng.randn(B, S + G, HID) * 0.1).astype(np.float32)

        # ground truth: non-cached run with an explicit causal mask
        total = S + G
        causal = np.where(np.tril(np.ones((total, total))) > 0, 0.0,
                          -1e30).astype(np.float32)[None, None]
        full = IF.fused_multi_transformer(
            t(x_full), **w, pre_layer_norm=True,
            attn_mask=t(causal), training=False)
        full = np.asarray(full.numpy())

        caches = _caches(B, NH, HD, S + G + 2, L)
        out_p, caches = IF.fused_multi_transformer(
            t(x_full[:, :S]), **w, pre_layer_norm=True,
            cache_kvs=caches, training=False)
        np.testing.assert_allclose(np.asarray(out_p.numpy()),
                                   full[:, :S], rtol=2e-4, atol=2e-5)
        for g in range(G):
            out_d, caches = IF.fused_multi_transformer(
                t(x_full[:, S + g:S + g + 1]), **w, pre_layer_norm=True,
                cache_kvs=caches, time_step=t(np.array([S + g], np.int32)),
                training=False)
            np.testing.assert_allclose(
                np.asarray(out_d.numpy())[:, 0], full[:, S + g],
                rtol=2e-4, atol=2e-5, err_msg=f"decode step {g}")

    def test_pre_caches_equal_split_prefill(self):
        """Splitting a prompt at P and feeding the first part's k/v as
        pre_caches must reproduce the full prefill's suffix outputs."""
        rng = np.random.RandomState(1)
        B, P, S2, HID, NH, FFN, L = 1, 3, 4, 8, 2, 16, 2
        HD = HID // NH
        w = _mk_stack(rng, L, HID, NH, FFN)
        x = (rng.randn(B, P + S2, HID) * 0.1).astype(np.float32)

        caches = _caches(B, NH, HD, P + S2, L)
        out_full, caches = IF.fused_multi_transformer(
            t(x), **w, pre_layer_norm=True, cache_kvs=caches,
            training=False)
        pre = [t(np.asarray(c.numpy())[:, :, :, :P].copy())
               for c in caches]

        caches2 = _caches(B, NH, HD, P + S2, L)
        out_sfx, caches2 = IF.fused_multi_transformer(
            t(x[:, P:]), **w, pre_layer_norm=True, cache_kvs=caches2,
            pre_caches=pre, training=False)
        np.testing.assert_allclose(
            np.asarray(out_sfx.numpy()),
            np.asarray(out_full.numpy())[:, P:], rtol=2e-4, atol=2e-5)
        # the prefix landed in the cache too
        np.testing.assert_allclose(
            np.asarray(caches2[0].numpy())[:, :, :, :P],
            np.asarray(pre[0].numpy()), rtol=1e-6)

    def test_rotary_decode_consistent_with_prefill(self):
        """With rotary embeddings, decode steps must agree with a
        one-shot prefill over the full sequence (two different code
        paths through the rotary + cache logic)."""
        rng = np.random.RandomState(2)
        B, S, G, HID, NH, FFN, L = 1, 4, 2, 8, 2, 16, 1
        HD = HID // NH
        w = _mk_stack(rng, L, HID, NH, FFN)
        total = S + G
        x = (rng.randn(B, total, HID) * 0.1).astype(np.float32)
        inv = 1.0 / (10000 ** (np.arange(0, HD, 2) / HD))
        pos = np.arange(total)[:, None] * inv[None]
        cos = np.repeat(np.cos(pos), 2, axis=-1)[None, None]
        sin = np.repeat(np.sin(pos), 2, axis=-1)[None, None]
        rot = np.stack([cos, sin]).astype(np.float32)  # [2,1,1,T,HD]

        caches = _caches(B, NH, HD, total, L)
        out_full, caches = IF.fused_multi_transformer(
            t(x), **w, pre_layer_norm=True, cache_kvs=caches,
            rotary_embs=t(rot), rotary_emb_dims=1, training=False)

        caches2 = _caches(B, NH, HD, total, L)
        out_p, caches2 = IF.fused_multi_transformer(
            t(x[:, :S]), **w, pre_layer_norm=True, cache_kvs=caches2,
            rotary_embs=t(rot[:, :, :, :S]), rotary_emb_dims=1,
            training=False)
        np.testing.assert_allclose(np.asarray(out_p.numpy()),
                                   np.asarray(out_full.numpy())[:, :S],
                                   rtol=2e-4, atol=2e-5)
        for g in range(G):
            out_d, caches2 = IF.fused_multi_transformer(
                t(x[:, S + g:S + g + 1]), **w, pre_layer_norm=True,
                cache_kvs=caches2,
                rotary_embs=t(rot[:, :, :, S + g:S + g + 1]),
                rotary_emb_dims=1,
                time_step=t(np.array([S + g], np.int32)), training=False)
            np.testing.assert_allclose(
                np.asarray(out_d.numpy())[:, 0],
                np.asarray(out_full.numpy())[:, S + g],
                rtol=2e-4, atol=2e-5, err_msg=f"rotary decode step {g}")

    def test_seq_lens_masks_padded_prompt(self):
        """A shorter prompt padded to S with garbage must produce the
        same prefill outputs (at valid positions) as the unpadded one."""
        rng = np.random.RandomState(3)
        B, S, HID, NH, FFN, L = 1, 6, 8, 2, 16, 1
        HD = HID // NH
        w = _mk_stack(rng, L, HID, NH, FFN)
        real = 4
        x = (rng.randn(B, S, HID) * 0.1).astype(np.float32)
        x_pad = x.copy()
        x_pad[:, real:] = 99.0   # garbage padding

        c1 = _caches(B, NH, HD, S, L)
        out1, _ = IF.fused_multi_transformer(
            t(x[:, :real]), **w, pre_layer_norm=True, cache_kvs=c1,
            training=False)
        c2 = _caches(B, NH, HD, S, L)
        out2, c2 = IF.fused_multi_transformer(
            t(x_pad), **w, pre_layer_norm=True, cache_kvs=c2,
            seq_lens=t(np.array([real], np.int32)), training=False)
        np.testing.assert_allclose(
            np.asarray(out2.numpy())[:, :real],
            np.asarray(out1.numpy()), rtol=2e-4, atol=2e-5)

        # ragged decode: the padded cache (garbage at [real, S)) must
        # produce the same next-token output as the unpadded cache —
        # the seq_lens mask keeps garbage slots out of the softmax
        nxt = (rng.randn(B, 1, HID) * 0.1).astype(np.float32)
        d1, _ = IF.fused_multi_transformer(
            t(nxt), **w, pre_layer_norm=True, cache_kvs=c1,
            seq_lens=t(np.array([real], np.int32)),
            time_step=t(np.array([real], np.int32)), training=False)
        d2, _ = IF.fused_multi_transformer(
            t(nxt), **w, pre_layer_norm=True, cache_kvs=c2,
            seq_lens=t(np.array([real], np.int32)),
            time_step=t(np.array([real], np.int32)), training=False)
        np.testing.assert_allclose(np.asarray(d2.numpy()),
                                   np.asarray(d1.numpy()),
                                   rtol=2e-4, atol=2e-5)

    def test_numpy_cache_kvs_updated(self):
        """Caches passed as raw numpy arrays must still come back
        updated (the returned list carries the new values)."""
        rng = np.random.RandomState(9)
        B, S, HID, NH, FFN, L = 1, 3, 8, 2, 16, 1
        HD = HID // NH
        w = _mk_stack(rng, L, HID, NH, FFN)
        x = (rng.randn(B, S, HID) * 0.1).astype(np.float32)
        np_caches = [np.zeros((2, B, NH, S, HD), np.float32)]
        _, out_caches = IF.fused_multi_transformer(
            t(x), **w, pre_layer_norm=True, cache_kvs=np_caches,
            training=False)
        assert np.abs(np.asarray(out_caches[0].numpy())).sum() > 0


class TestFusedMHACache:
    def test_cache_growth_matches_full_run_last_token(self):
        """Reference cache_kv semantics: plain (non-causal) attention
        over [cache; new]. A multi-token append over an empty cache must
        therefore equal the non-cached run at EVERY position, and the
        subsequent single-token decode must equal the full run's last
        row."""
        rng = np.random.RandomState(4)
        B, S, HID, NH = 2, 5, 16, 2
        HD = HID // NH
        qkv_w = t((rng.randn(3, NH, HD, HID) * 0.05).astype(np.float32))
        qkv_b = t((rng.randn(3, NH, HD) * 0.05).astype(np.float32))
        lin_w = t((rng.randn(HID, HID) * 0.05).astype(np.float32))
        lin_b = t((rng.randn(HID) * 0.05).astype(np.float32))
        ln_s = t(np.ones(HID, np.float32))
        ln_b = t(np.zeros(HID, np.float32))
        x = (rng.randn(B, S, HID) * 0.1).astype(np.float32)

        full = IF.fused_multi_head_attention(
            t(x), qkv_w, lin_w, pre_layer_norm=True, pre_ln_scale=ln_s,
            pre_ln_bias=ln_b, qkv_bias=qkv_b, linear_bias=lin_b,
            dropout_rate=0.0, attn_dropout_rate=0.0, training=False)
        full = np.asarray(full.numpy())

        empty = t(np.zeros((2, B, NH, 0, HD), np.float32))
        out_pre, cache = IF.fused_multi_head_attention(
            t(x[:, :S - 1]), qkv_w, lin_w, pre_layer_norm=True,
            pre_ln_scale=ln_s, pre_ln_bias=ln_b, qkv_bias=qkv_b,
            linear_bias=lin_b, cache_kv=empty, dropout_rate=0.0,
            attn_dropout_rate=0.0, training=False)
        assert list(cache.shape) == [2, B, NH, S - 1, HD]
        # multi-token append == the non-cached run over the same prefix
        full_pre = IF.fused_multi_head_attention(
            t(x[:, :S - 1]), qkv_w, lin_w, pre_layer_norm=True,
            pre_ln_scale=ln_s, pre_ln_bias=ln_b, qkv_bias=qkv_b,
            linear_bias=lin_b, dropout_rate=0.0,
            attn_dropout_rate=0.0, training=False)
        np.testing.assert_allclose(np.asarray(out_pre.numpy()),
                                   np.asarray(full_pre.numpy()),
                                   rtol=2e-4, atol=2e-5)
        out, cache = IF.fused_multi_head_attention(
            t(x[:, S - 1:]), qkv_w, lin_w, pre_layer_norm=True,
            pre_ln_scale=ln_s, pre_ln_bias=ln_b, qkv_bias=qkv_b,
            linear_bias=lin_b, cache_kv=cache, dropout_rate=0.0,
            attn_dropout_rate=0.0, training=False)
        assert list(cache.shape) == [2, B, NH, S, HD]
        np.testing.assert_allclose(np.asarray(out.numpy())[:, 0],
                                   full[:, -1], rtol=2e-4, atol=2e-5)


class TestFusedMultiTransformerLayer:
    def test_layer_decode_roundtrip(self):
        """The FusedMultiTransformer layer drives the cached path:
        prefill + one decode step agree with one full prefill."""
        from paddle_tpu.incubate.nn import FusedMultiTransformer
        rng = np.random.RandomState(7)
        B, S, HID, NH, FFN, L = 1, 4, 8, 2, 16, 2
        HD = HID // NH
        lyr = FusedMultiTransformer(HID, NH, FFN, num_layers=L)
        lyr.eval()
        x = (rng.randn(B, S + 1, HID) * 0.1).astype(np.float32)

        c1 = _caches(B, NH, HD, S + 1, L)
        out_full, _ = lyr(t(x), caches=c1)
        c2 = _caches(B, NH, HD, S + 1, L)
        _, c2 = lyr(t(x[:, :S]), caches=c2)
        out_d, _ = lyr(t(x[:, S:]), caches=c2,
                       time_step=t(np.array([S], np.int32)))
        np.testing.assert_allclose(
            np.asarray(out_d.numpy())[:, 0],
            np.asarray(out_full.numpy())[:, S], rtol=2e-4, atol=2e-5)


class TestVarlenPreCache:
    def test_prefix_always_attendable(self):
        """pre_cache_length=P: prefix keys bypass kv_seq_lens and the
        causal rule; equivalent to a manual softmax over [prefix; live
        suffix]."""
        import jax.numpy as jnp
        import jax
        rng = np.random.RandomState(5)
        B, H, SQ, P, SK_body, D = 1, 1, 3, 2, 4, 8
        SK = P + SK_body
        q = (rng.randn(B, H, SQ, D) * 0.3).astype(np.float32)
        k = (rng.randn(B, H, SK, D) * 0.3).astype(np.float32)
        v = (rng.randn(B, H, SK, D) * 0.3).astype(np.float32)
        kl = 3   # only 3 of the 4 body keys live

        out = IF.variable_length_memory_efficient_attention(
            t(q), t(k), t(v), t(np.array([SQ], np.int32)),
            t(np.array([kl], np.int32)), causal=True, pre_cache_length=P)
        out = np.asarray(out.numpy())

        # manual: query i sees prefix (all P) + body j<=i (j<kl)
        sc = (q[0, 0] @ k[0, 0].T) / np.sqrt(D)
        for i in range(SQ):
            for j in range(SK):
                body_j = j - P
                if j >= P and (body_j > i + (SK - P - SQ)
                               or body_j >= kl):
                    sc[i, j] = -1e30
        p = np.exp(sc - sc.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want = p @ v[0, 0]
        np.testing.assert_allclose(out[0, 0], want, rtol=2e-4, atol=2e-5)


class TestGenerateRunCache:
    """The compiled generate runner must be reused across calls (a fresh
    jit per call costs a full retrace per serving request) but must NOT
    serve stale constants after the config object is mutated."""

    def _cfg(self):
        from paddle_tpu.models.llama import LlamaConfig
        return LlamaConfig(vocab_size=97, hidden_size=32,
                           intermediate_size=64, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           max_position_embeddings=64)

    def test_runner_reused_for_same_shape(self):
        import jax.numpy as jnp
        from paddle_tpu.inference import generation as G
        from paddle_tpu.models.llama import init_params
        import jax
        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        toks = jnp.zeros((1, 8), jnp.int32)
        g = G.GenerationConfig(max_new_tokens=4, greedy=True)
        G._RUN_CACHE.clear()
        out1 = G.generate(params, toks, cfg, g)
        n_after_first = len(G._RUN_CACHE)
        out2 = G.generate(params, toks, cfg, g)
        assert n_after_first == 1
        assert len(G._RUN_CACHE) == 1          # no second entry
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))

    def test_mutated_config_misses_cache(self):
        import jax.numpy as jnp
        from paddle_tpu.inference import generation as G
        from paddle_tpu.models.llama import init_params
        import jax
        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        toks = jnp.zeros((1, 8), jnp.int32)
        g = G.GenerationConfig(max_new_tokens=4, greedy=True)
        G._RUN_CACHE.clear()
        G.generate(params, toks, cfg, g)
        cfg.rope_theta = cfg.rope_theta * 2   # mutate in place
        G.generate(params, toks, cfg, g)
        # value-keyed cache: the mutated config must get its own runner
        assert len(G._RUN_CACHE) == 2


class TestTrainerBatchStaging:
    def test_already_placed_array_passes_through(self):
        """An input whose sharding already matches must NOT be re-put
        (each re-put is a blocking h2d roundtrip per step)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        from paddle_tpu.distributed.trainer import (MeshConfig, Trainer,
                                                    make_mesh)
        import jax.numpy as jnp
        mesh = make_mesh(MeshConfig())
        tr = Trainer(lambda p, t: jnp.sum(p["w"]) * 0.0, mesh,
                     {"w": PartitionSpec()}, lr=1e-3)
        x = jnp.zeros((4, 8), jnp.int32)
        staged = jax.device_put(x, NamedSharding(mesh, tr.data_spec))
        assert tr._stage_batch(staged) is staged
        # host numpy still gets placed
        out = tr._stage_batch(np.zeros((4, 8), np.int32))
        assert isinstance(out, jax.Array)


class TestFusedOptimizerPath:
    def test_fused_matches_per_leaf_update(self):
        """Trainer(fused_optimizer=True) — the flat Pallas AdamW path
        (interpret mode off-TPU) must track the per-leaf XLA update."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec
        from paddle_tpu.distributed.trainer import (MeshConfig, Trainer,
                                                    make_mesh)

        def loss_fn(p, x):
            h = jnp.tanh(x @ p["w1"])
            return jnp.mean(jnp.square(h @ p["w2"]))

        rng = np.random.RandomState(0)
        params = {"w1": jnp.asarray(rng.randn(8, 16), jnp.float32),
                  "w2": jnp.asarray(rng.randn(16, 4), jnp.float32)}
        specs = {"w1": PartitionSpec(), "w2": PartitionSpec()}
        x = jnp.asarray(rng.randn(32, 8), jnp.float32)
        mesh = make_mesh(MeshConfig())

        outs = {}
        for fused in (False, True):
            tr = Trainer(loss_fn, mesh, specs, lr=1e-2, grad_clip=1.0,
                         fused_optimizer=fused, donate=False)
            st = tr.init_state(dict(params))
            for _ in range(3):
                st, m = tr.step(st, x)
            outs[fused] = (np.asarray(m["loss"]),
                           np.asarray(m["grad_norm"]),
                           {k: np.asarray(v) for k, v in st.params.items()})
        np.testing.assert_allclose(outs[True][0], outs[False][0],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(outs[True][1], outs[False][1],
                                   rtol=1e-5, atol=1e-6)
        for k in params:
            np.testing.assert_allclose(outs[True][2][k], outs[False][2][k],
                                       rtol=1e-4, atol=1e-5)

    def test_forced_fused_rejects_multidevice_and_mixed_dtype(self):
        """fused_optimizer=True must fail loudly where auto would
        decline: flat unsharded state on a multi-device mesh silently
        loses FSDP sharding, and mixed dtypes get silently cast."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec
        from paddle_tpu.distributed.trainer import (MeshConfig, Trainer,
                                                    make_mesh)

        def loss_fn(p, x):
            return jnp.mean(jnp.square(x @ p["w"]))

        devs = np.array(jax.devices()[:2]).reshape(2, 1, 1)
        mesh2 = Mesh(devs, ("dp", "fsdp", "sp"))
        tr = Trainer(loss_fn, mesh2, {"w": PartitionSpec()},
                     fused_optimizer=True)
        with pytest.raises(ValueError, match="UNSHARDED"):
            tr.init_state({"w": jnp.ones((4, 4), jnp.float32)})

        mesh1 = make_mesh(MeshConfig())
        # TWO non-fp32 dtypes: no single shadow can cover both
        tr = Trainer(lambda p, x: jnp.mean(x @ p["w"]), mesh1,
                     {"w": PartitionSpec(), "b": PartitionSpec()},
                     fused_optimizer=True)
        with pytest.raises(ValueError, match="floating"):
            tr.init_state({"w": jnp.ones((4, 4), jnp.float16),
                           "b": jnp.ones((4,), jnp.bfloat16)})

    def test_fused_mixed_dtype_tree_matches_per_leaf(self):
        """The llama layout (bf16 weights + fp32 norms) must run the
        fused path: fp32 leaves slice back exact from the master, bf16
        leaves from the shadow; three steps track the per-leaf update."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec
        from paddle_tpu.distributed.trainer import (MeshConfig, Trainer,
                                                    make_mesh)

        def loss_fn(p, x):
            h = jnp.tanh(x @ p["w"].astype(jnp.float32))
            return jnp.mean(jnp.square(h * p["scale"]))

        rng = np.random.RandomState(0)
        params = {"w": jnp.asarray(rng.randn(8, 16), jnp.bfloat16),
                  "scale": jnp.ones((16,), jnp.float32)}
        specs = {"w": PartitionSpec(), "scale": PartitionSpec()}
        x = jnp.asarray(rng.randn(32, 8), jnp.float32)
        mesh = make_mesh(MeshConfig())

        outs = {}
        for fused in (False, True):
            tr = Trainer(loss_fn, mesh, specs, lr=1e-2, grad_clip=1.0,
                         fused_optimizer=fused, donate=False)
            st = tr.init_state(dict(params))
            assert tr._fused == fused
            for _ in range(3):
                st, m = tr.step(st, x)
            outs[fused] = (np.asarray(m["loss"]),
                           {k: np.asarray(v, np.float32)
                            for k, v in st.params.items()})
        np.testing.assert_allclose(outs[True][0], outs[False][0],
                                   rtol=1e-3, atol=1e-4)
        for k in params:
            np.testing.assert_allclose(outs[True][1][k], outs[False][1][k],
                                       rtol=2e-2, atol=2e-3)
        # dtypes preserved through the fused update
        tr = Trainer(loss_fn, mesh, specs, fused_optimizer=True,
                     donate=False)
        st = tr.init_state(dict(params))
        st, _ = tr.step(st, x)
        assert st.params["w"].dtype == jnp.bfloat16
        assert st.params["scale"].dtype == jnp.float32

    def test_fused_bf16_moment_dtype(self):
        """moment_dtype=bfloat16 halves mu/nu storage; the update still
        descends and state dtypes stay step-invariant (donation)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec
        from paddle_tpu.distributed.trainer import (MeshConfig, Trainer,
                                                    make_mesh)

        def loss_fn(p, x):
            return jnp.mean(jnp.square(x @ p["w"]))

        rng = np.random.RandomState(2)
        mesh = make_mesh(MeshConfig())
        for fused in (False, True):
            tr = Trainer(loss_fn, mesh, {"w": PartitionSpec()}, lr=1e-2,
                         fused_optimizer=fused, donate=False,
                         moment_dtype=jnp.bfloat16)
            st = tr.init_state(
                {"w": jnp.asarray(rng.randn(16, 4), jnp.float32)})
            assert jax.tree_util.tree_leaves(st.mu)[0].dtype == jnp.bfloat16
            losses = []
            for _ in range(5):
                st, m = tr.step(
                    st, jnp.asarray(rng.randn(32, 16), jnp.float32))
                losses.append(float(m["loss"]))
                assert jax.tree_util.tree_leaves(st.mu)[0].dtype \
                    == jnp.bfloat16
            assert losses[-1] < losses[0]

    def test_fused_with_nan_check(self):
        """FLAGS_check_nan_inf rebuilds the step without donation; the
        fused path must survive the rebuild and report finite metrics."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec
        from paddle_tpu.core.flags import GLOBAL_FLAGS
        from paddle_tpu.distributed.trainer import (MeshConfig, Trainer,
                                                    make_mesh)

        def loss_fn(p, x):
            return jnp.mean(jnp.square(x @ p["w"]))

        rng = np.random.RandomState(1)
        params = {"w": jnp.asarray(rng.randn(8, 4), jnp.float32)}
        mesh = make_mesh(MeshConfig())
        tr = Trainer(loss_fn, mesh, {"w": PartitionSpec()}, lr=1e-2,
                     fused_optimizer=True)
        st = tr.init_state(params)
        x = jnp.asarray(rng.randn(16, 8), jnp.float32)
        GLOBAL_FLAGS.set("check_nan_inf", True)
        try:
            st, m = tr.step(st, x)
            assert np.isfinite(float(m["loss"]))
            # a poisoned batch must raise, not silently update
            bad = x.at[0, 0].set(jnp.nan)
            try:
                tr.step(st, bad)
                raised = False
            except FloatingPointError:
                raised = True
            assert raised
        finally:
            GLOBAL_FLAGS.set("check_nan_inf", False)
