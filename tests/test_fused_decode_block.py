"""Fused decode-block megakernels (ops/pallas/fused_decode_block.py),
the kernel registry (ops/pallas/registry.py), and the PR's satellites
(autotune-cache robustness, per-kernel bench gate, paged-decode
pages-per-step tuning).

Parity contract: wherever registry dispatch selects the ``unfused``
composition (always on CPU/interpret), the fused decode step is
BIT-identical to the pre-fusion ``_paged_decode_step`` — asserted
through a >=20-request ServingEngine stream and at the step level.
The Pallas megakernels themselves (forced, interpret mode) match the
composition to fp32 roundoff across randomized shapes, fp32 and int8
cache.
"""
import functools
import importlib.util
import json
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.models import llama
from paddle_tpu.inference import GenerationConfig, ServingEngine
from paddle_tpu.inference.generation import (_fused_decode_step,
                                             _fused_mode,
                                             _paged_decode_step,
                                             generate_paged)
from paddle_tpu.ops.pallas import fused_decode_block as fdb
from paddle_tpu.ops.pallas.registry import KernelRegistry

pytestmark = pytest.mark.fused

CFG = llama.LlamaConfig(vocab_size=97, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        max_position_embeddings=128, dtype=jnp.float32,
                        remat=False)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _engine(params, **kw):
    kw.setdefault("capacity", 3)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("max_seq_len", 64)
    return ServingEngine(params, CFG, **kw)


def _rope_tables(T, hd):
    inv = 1.0 / (10000.0 ** (np.arange(0, hd, 2) / hd))
    t = np.arange(T)[:, None] * inv[None, :]
    return jnp.asarray(np.sin(t), jnp.float32), \
        jnp.asarray(np.cos(t), jnp.float32)


def _attn_case(rng, B, D, KV, groups, hd, BS, MB, quant=False):
    H = KV * groups
    N = B * MB + 2
    dt = jnp.float32
    mk = lambda *s: jnp.asarray(rng.randn(*s) * 0.07, dt)  # noqa: E731
    x = mk(B, D)
    nw = jnp.asarray(rng.rand(D) + 0.5, dt)
    wq, wk, wv = mk(D, H * hd), mk(D, KV * hd), mk(D, KV * hd)
    wo = mk(H * hd, D)
    sin, cos = _rope_tables(BS * MB, hd)
    bt = jnp.asarray(rng.permutation(N)[:B * MB].reshape(B, MB),
                     jnp.int32)
    # one slot mid-page, one empty (seq_len 0: only the new token), one
    # page-aligned when B allows
    lens = [int(rng.randint(1, BS * MB)), 0] + \
        [int(rng.randint(0, BS * MB)) for _ in range(B - 2)]
    lens = jnp.asarray(lens[:B], jnp.int32)
    if quant:
        kp = jnp.asarray(rng.randint(-127, 128, (N, BS, KV, hd)),
                         jnp.int8)
        vp = jnp.asarray(rng.randint(-127, 128, (N, BS, KV, hd)),
                         jnp.int8)
        scales = (jnp.asarray(rng.rand(KV) * 0.1 + 0.01, jnp.float32),
                  jnp.asarray(rng.rand(KV) * 0.1 + 0.01, jnp.float32))
    else:
        kp, vp = mk(N, BS, KV, hd), mk(N, BS, KV, hd)
        scales = None
    return (x, nw, wq, wk, wv, wo, sin, cos, kp, vp, bt, lens), scales


# ---------------------------------------------------------------------------
# kernel-level parity (forced Pallas, interpret mode) — randomized shapes
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_attn_block_parity_randomized(seed):
    rng = np.random.RandomState(seed)
    B = int(rng.randint(1, 4))
    KV = int(rng.choice([1, 2, 4]))
    groups = int(rng.choice([1, 2, 3]))
    hd = int(rng.choice([8, 16, 32]))
    BS = int(rng.choice([4, 8, 16]))
    MB = int(rng.randint(2, 5))
    D = int(rng.choice([32, 48, 64]))
    args, _ = _attn_case(rng, B, D, KV, groups, hd, BS, MB)
    xf, kf, vf = fdb.fused_attn_block_pallas(*args)
    xr, kr, vr = fdb.attn_block_ref(*args)
    np.testing.assert_allclose(np.asarray(xf), np.asarray(xr),
                               atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(kf), np.asarray(kr),
                               atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(vf), np.asarray(vr),
                               atol=2e-5, rtol=1e-5)


def test_attn_block_parity_int8_cache():
    rng = np.random.RandomState(3)
    args, scales = _attn_case(rng, B=2, D=64, KV=2, groups=2, hd=16,
                              BS=8, MB=3, quant=True)
    xf, kf, vf = fdb.fused_attn_block_pallas(*args, kv_scales=scales)
    xr, kr, vr = fdb.attn_block_ref(*args, kv_scales=scales)
    # the fused kernel folds dequant(quant(new K/V)) in VMEM; the ref
    # reads the same values back from the int8 pool — fp32 roundoff only
    np.testing.assert_allclose(np.asarray(xf), np.asarray(xr),
                               atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(kf), np.asarray(kr),
                               atol=2e-5, rtol=1e-5)


def test_attn_block_pages_per_step_invariant():
    """pages_per_step only changes pipelining: pages are still processed
    sequentially in order, so the online softmax is bit-identical."""
    rng = np.random.RandomState(4)
    args, _ = _attn_case(rng, B=2, D=32, KV=2, groups=2, hd=16, BS=4,
                         MB=4)
    outs = [fdb.fused_attn_block_pallas(*args, pages_per_step=pp)[0]
            for pp in (1, 2, 4)]
    np.testing.assert_array_equal(np.asarray(outs[0]),
                                  np.asarray(outs[1]))
    np.testing.assert_array_equal(np.asarray(outs[0]),
                                  np.asarray(outs[2]))


@pytest.mark.parametrize("D,F", [(32, 64), (64, 256), (48, 96)])
def test_mlp_block_parity(D, F):
    rng = np.random.RandomState(D + F)
    dt = jnp.float32
    mk = lambda *s: jnp.asarray(rng.randn(*s) * 0.07, dt)  # noqa: E731
    x, nw = mk(3, D), jnp.asarray(rng.rand(D) + 0.5, dt)
    wg, wu, wd = mk(D, F), mk(D, F), mk(F, D)
    got = fdb.fused_mlp_block_pallas(x, nw, wg, wu, wd)
    want = fdb.mlp_block_ref(x, nw, wg, wu, wd)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=1e-5)
    # tiling over F changes only the accumulation grouping (fp32 acc)
    tiled = fdb.fused_mlp_block_pallas(x, nw, wg, wu, wd,
                                       block_f=F // 2)
    np.testing.assert_allclose(np.asarray(tiled), np.asarray(want),
                               atol=2e-5, rtol=1e-5)


def test_mlp_candidates_divide_evenly():
    """A ragged last tile would multiply garbage columns into the
    accumulator — candidates must divide F exactly."""
    for F in (96, 128, 512, 1024, 4096):
        cands = fdb._mlp_candidates(F)
        assert cands, F
        assert all(F % c == 0 for c in cands), (F, cands)
    assert fdb._mlp_candidates(100) == [100]   # no divisor candidate


# ---------------------------------------------------------------------------
# single-launch decode block (r20): kernel parity, dispatch contract,
# mode="block" plumbing
# ---------------------------------------------------------------------------
def _block_case(rng, wq_bits=0, quant=False):
    """Full-block args at the clamp-edge decode shapes: the attention
    case above + post-norm and SwiGLU weights (ragged F), the weight
    tree optionally PTQ-quantized (down_proj packs its F rows)."""
    B, D, KV, groups, hd, BS, MB, F = 2, 32, 2, 1, 16, 8, 3, 96
    args, scales = _attn_case(rng, B, D, KV, groups, hd, BS, MB,
                              quant=quant)
    (x, nw, wq, wk, wv, wo, sin, cos, kp, vp, bt, lens) = args
    mk = lambda *s: jnp.asarray(rng.randn(*s) * 0.07,    # noqa: E731
                                jnp.float32)
    pw = jnp.asarray(rng.rand(D) + 0.5, jnp.float32)
    wg, wu, wd = mk(D, F), mk(D, F), mk(F, D)
    ws = (wq, wk, wv, wo, wg, wu, wd)
    if wq_bits:
        from paddle_tpu.quantization import ptq as _ptq
        ws = tuple(_ptq.quantize_leaf(w, wq_bits)
                   for w in (wq, wk, wv, wo, wg, wu)) \
            + (_ptq.quantize_leaf(wd, wq_bits, pack_axis=1),)
    return (x, nw, ws[0], ws[1], ws[2], ws[3], pw, ws[4], ws[5],
            ws[6], sin, cos, kp, vp, bt, lens), scales


@pytest.mark.parametrize("wq_bits", [0, 8, 4], ids=["fp", "w8", "w4"])
def test_decode_block_single_launch_parity(wq_bits):
    """The single-launch megakernel (forced, interpret) matches the
    priority-0 composed route to fp32 roundoff — the attn->MLP residual
    handoff through f32 VMEM scratch changes only op grouping. Plain,
    int8 and packed-int4 weight trees."""
    rng = np.random.RandomState(20 + wq_bits)
    full, _ = _block_case(rng, wq_bits=wq_bits)
    got = fdb.fused_decode_block_pallas(*full, pages_per_step=2,
                                        block_f=32)
    want = fdb.decode_block_composed(*full)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   atol=5e-5, rtol=1e-5)


def test_decode_block_parity_int8_pool_and_tunable_invariance():
    """int8 KV pool (dequant in VMEM, scales per head) and the joint
    (pages_per_step, block_f) tunables: every choice is the same math
    to fp32 roundoff."""
    rng = np.random.RandomState(30)
    full, scales = _block_case(rng, quant=True)
    want = fdb.decode_block_composed(*full, kv_scales=scales)
    for pp, bf in ((1, 96), (2, 32), (4, 48)):
        got = fdb.fused_decode_block_pallas(*full, kv_scales=scales,
                                            pages_per_step=pp,
                                            block_f=bf)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=5e-5, rtol=1e-5,
                                       err_msg=f"pp={pp} bf={bf}")


def test_block_dispatch_flagship_weight_quant_contract():
    """The acceptance bar: at the flagship serving class the combined
    bf16 attn+MLP windows exceed the scoped-VMEM envelope (two-kernel
    composed route, reason naming the envelope), while int8/int4 weight
    variants fit and dispatch the single-launch kernel."""
    from paddle_tpu.ops.pallas.registry import KERNELS

    def m(wq=None):
        meta = fdb.decode_meta_dims(8, 1024, 16, 16, 64, 4096, 16, 24,
                                    jnp.bfloat16, jnp.bfloat16, False,
                                    weight_dtype=wq)
        meta["interpret"] = False
        return meta
    assert KERNELS.dispatch("decode_block_fused", m())[0] == "composed"
    assert KERNELS.dispatch("decode_block_fused",
                            m("int8"))[0] == "pallas_block"
    assert KERNELS.dispatch("decode_block_fused",
                            m("int4"))[0] == "pallas_block"
    rej = [r for r in KERNELS.explain("decode_block_fused", m())
           if r["name"] == "pallas_block"][0]
    assert not rej["supported"] and "envelope" in rej["reason"]


def test_block_mode_resolver_contract():
    """mode='block' pins the single-launch kernel through
    resolve_decode_step; auto on CPU keeps the composed tier (per-stage
    fns, bit parity); the two-stage resolver refuses 'block' with a
    pointer at resolve_decode_step."""
    meta = fdb.decode_meta(CFG, B=2, BS=4, MB=4,
                           pool_dtype=jnp.float32, quant=False)
    b_fn, a_fn, m_fn, names = fdb.resolve_decode_step(meta, "block")
    assert b_fn is not None and a_fn is None and m_fn is None
    assert names == {"block": "pallas_block", "attn": "pallas_block",
                     "mlp": "pallas_block"}
    b_fn, a_fn, m_fn, names = fdb.resolve_decode_step(meta, "auto")
    assert b_fn is None and a_fn is not None and m_fn is not None
    assert names == {"block": "composed", "attn": "unfused",
                     "mlp": "unfused"}
    with pytest.raises(ValueError, match="resolve_decode_step"):
        fdb.resolve_decode_blocks(meta, "block")
    with pytest.raises(ValueError, match="auto|pallas|ref|block"):
        fdb.resolve_decode_step(meta, "bogus")
    assert _fused_mode("block") == "block"


def test_paged_decode_pages_per_step_invariant():
    """Satellite: the unfused paged-decode kernel's pages-per-step is an
    autotune candidate now — every choice must stay bit-identical."""
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_attention_decode_pallas)
    rng = np.random.RandomState(5)
    B, H, KV, hd, BS, MB, N = 3, 4, 2, 16, 4, 4, 14
    q = jnp.asarray(rng.randn(B, H, hd) * 0.1, jnp.float32)
    kp = jnp.asarray(rng.randn(N, BS, KV, hd) * 0.1, jnp.float32)
    vp = jnp.asarray(rng.randn(N, BS, KV, hd) * 0.1, jnp.float32)
    bt = jnp.asarray(rng.permutation(N)[:B * MB].reshape(B, MB),
                     jnp.int32)
    lens = jnp.asarray([0, 7, BS * MB - 1], jnp.int32)
    outs = [np.asarray(paged_attention_decode_pallas(
        q, kp, vp, bt, lens, pages_per_step=pp)) for pp in (1, 2, 4)]
    np.testing.assert_array_equal(outs[0], outs[1])
    np.testing.assert_array_equal(outs[0], outs[2])


# ---------------------------------------------------------------------------
# registry dispatch
# ---------------------------------------------------------------------------
def test_registry_priority_and_fallback():
    reg = KernelRegistry()
    reg.register("op", "fast", lambda: "fast", priority=10,
                 supports=lambda m: (m["n"] < 8, "n too big"))
    reg.register("op", "ref", lambda: "ref", priority=0)
    assert reg.dispatch("op", {"n": 4})[0] == "fast"
    assert reg.dispatch("op", {"n": 100})[0] == "ref"
    ex = reg.explain("op", {"n": 100})
    assert [e["name"] for e in ex] == ["fast", "ref"]
    assert not ex[0]["supported"] and ex[0]["reason"] == "n too big"
    assert ex[1]["selected"]


def test_registry_latest_wins_and_errors():
    reg = KernelRegistry()
    reg.register("op", "v", lambda: 1)
    reg.register("op", "v", lambda: 2)          # replaces, no duplicate
    assert len(reg.variants("op")) == 1
    assert reg.variant("op", "v").fn() == 2
    with pytest.raises(KeyError):
        reg.dispatch("missing", {})
    with pytest.raises(KeyError):
        reg.variant("op", "nope")
    reg.register("op2", "only", lambda: 0, supports=lambda m: False)
    with pytest.raises(RuntimeError, match="no variant"):
        reg.dispatch("op2", {})


def test_registry_force_stacks():
    reg = KernelRegistry()
    reg.register("op", "a", lambda: "a", priority=10)
    reg.register("op", "b", lambda: "b", priority=0)
    assert reg.dispatch("op", {})[0] == "a"
    with reg.force("op", "b"):
        assert reg.dispatch("op", {})[0] == "b"
        with reg.force("op", "a"):
            assert reg.dispatch("op", {})[0] == "a"
        assert reg.dispatch("op", {})[0] == "b"
    assert reg.dispatch("op", {})[0] == "a"
    with pytest.raises(KeyError):
        reg.force("op", "typo")


def test_dispatch_interpret_falls_back_unfused():
    """On CPU (interpret mode) auto dispatch must select the unfused
    composition — that is what makes the engine parity exact."""
    meta = fdb.decode_meta(CFG, B=2, BS=4, MB=4,
                           pool_dtype=jnp.float32, quant=False)
    assert meta["interpret"]
    attn_fn, mlp_fn, names = fdb.resolve_decode_blocks(meta, "auto")
    assert names == {"attn": "unfused", "mlp": "unfused"}
    assert attn_fn is fdb.attn_block_ref
    assert mlp_fn is fdb.mlp_block_ref
    # forcing still returns the Pallas variants (tests / audit catalog)
    _, _, forced = fdb.resolve_decode_blocks(meta, "pallas")
    assert forced == {"attn": "pallas_fused", "mlp": "pallas_fused"}
    with pytest.raises(ValueError, match="auto|pallas|ref"):
        fdb.resolve_decode_blocks(meta, "bogus")


def test_vmem_budget_gates_fused_variant(monkeypatch):
    """Oversized block weights must fail the ``supports`` predicate with
    a reason naming the VMEM budget, even off interpret mode. The
    budget rides IN the meta (decode_meta reads the env at build time —
    i.e. at trace time, when the _PAGED_CACHE route key is computed),
    so the shrunken-budget meta is rebuilt the way a retrace would."""
    meta = fdb.decode_meta(CFG, B=2, BS=4, MB=4,
                           pool_dtype=jnp.float32, quant=False)
    meta["interpret"] = False
    assert meta["vmem_budget"] == fdb._vmem_budget()
    ok, why = fdb._supports_attn(dict(meta))
    assert ok, why                               # tiny cfg fits
    monkeypatch.setenv("PADDLE_TPU_FUSED_VMEM_BUDGET", "1024")
    meta = fdb.decode_meta(CFG, B=2, BS=4, MB=4,
                           pool_dtype=jnp.float32, quant=False)
    meta["interpret"] = False
    assert meta["vmem_budget"] == 1024
    ok, why = fdb._supports_attn(dict(meta))
    assert not ok and "VMEM" in why
    ok, why = fdb._supports_mlp(dict(meta))
    assert not ok and "VMEM" in why


# ---------------------------------------------------------------------------
# decode-step + engine parity (the acceptance bar)
# ---------------------------------------------------------------------------
def _step_inputs(params, rng, B=2, BS=4, MB=4, quant=False):
    L = CFG.num_hidden_layers
    KV, hd = CFG.num_key_value_heads, CFG.head_dim
    N = B * MB + 1
    if quant:
        kp = jnp.asarray(rng.randint(-127, 128, (L, N, BS, KV, hd)),
                         jnp.int8)
        vp = jnp.asarray(rng.randint(-127, 128, (L, N, BS, KV, hd)),
                         jnp.int8)
        scales = (
            jnp.asarray(rng.rand(L, KV) * 0.1 + 0.01, jnp.float32),
            jnp.asarray(rng.rand(L, KV) * 0.1 + 0.01, jnp.float32))
    else:
        kp = jnp.asarray(rng.randn(L, N, BS, KV, hd) * 0.1, jnp.float32)
        vp = jnp.asarray(rng.randn(L, N, BS, KV, hd) * 0.1, jnp.float32)
        scales = None
    tok = jnp.asarray(rng.randint(0, 97, (B,)), jnp.int32)
    bt = jnp.asarray(rng.permutation(N)[:B * MB].reshape(B, MB),
                     jnp.int32)
    lens = jnp.asarray([5, 0][:B], jnp.int32)
    return tok, kp, vp, bt, lens, scales


@pytest.mark.parametrize("quant", [False, True],
                         ids=["fp32", "int8"])
def test_fused_step_bit_parity_and_pallas_closeness(params, quant):
    """mode='auto' (composition on CPU) is BIT-identical to the
    pre-fusion step; mode='pallas' (forced megakernels, interpret)
    matches to fp32 roundoff — fp32 and int8 cache."""
    rng = np.random.RandomState(6 + quant)
    tok, kp, vp, bt, lens, scales = _step_inputs(params, rng,
                                                 quant=quant)
    lg0, kp0, vp0 = _paged_decode_step(params, tok, CFG, kp, vp, bt,
                                       lens, kv_scales=scales)
    lg1, kp1, vp1 = _fused_decode_step(params, tok, CFG, kp, vp, bt,
                                       lens, kv_scales=scales,
                                       mode="auto")
    np.testing.assert_array_equal(np.asarray(lg0), np.asarray(lg1))
    np.testing.assert_array_equal(np.asarray(kp0), np.asarray(kp1))
    np.testing.assert_array_equal(np.asarray(vp0), np.asarray(vp1))
    lg2, kp2, vp2 = _fused_decode_step(params, tok, CFG, kp, vp, bt,
                                       lens, kv_scales=scales,
                                       mode="pallas")
    np.testing.assert_allclose(np.asarray(lg2), np.asarray(lg0),
                               atol=5e-5, rtol=1e-5)
    # the megakernel's QKV+rope op order differs from the composition
    # by fp32 roundoff, so the written pool values are 1-ulp close (and
    # EXACTLY equal under int8, where quantization re-snaps them)
    assert_pool = np.testing.assert_array_equal if quant else \
        functools.partial(np.testing.assert_allclose, atol=1e-6,
                          rtol=1e-5)
    assert_pool(np.asarray(kp2), np.asarray(kp0))
    assert_pool(np.asarray(vp2), np.asarray(vp0))


@pytest.mark.parametrize("cdt", [None, "int8"], ids=["fp32", "int8"])
def test_engine_stream_fused_vs_unfused_bit_parity(params, cdt):
    """>=20-request mixed-length greedy stream: the fused-decode engine
    (default-on flag) must produce bit-identical tokens to an engine
    pinned to the pre-fusion step, and keep the zero-retrace steady
    state (1 decode program, <=1 trace per prefill bucket)."""
    rng = np.random.RandomState(7)
    specs = [(int(rng.randint(3, 15)), int(rng.randint(2, 6)))
             for _ in range(22)]
    prompts = [rng.randint(0, 97, (S,)).astype(np.int32)
               for S, _ in specs]

    def run(fused):
        eng = _engine(params, cache_dtype=cdt, fused_decode=fused)
        rs = [eng.submit(p, GenerationConfig(max_new_tokens=N,
                                             greedy=True))
              for p, (_, N) in zip(prompts, specs)]
        eng.drain()
        assert all(r.done for r in rs)
        return eng, [r.tokens for r in rs]

    eng_f, toks_f = run(None)      # flag default: fused auto
    eng_u, toks_u = run(False)     # pinned pre-fusion step
    assert toks_f == toks_u
    c = eng_f.counters
    assert c["requests_completed"] == 22
    assert c["decode_traces"] == 1, c
    assert set(c["prefill_traces"]) <= {8, 16}
    assert all(n <= 1 for n in c["prefill_traces"].values()), c
    assert eng_f.metrics()["decode_variant"]["mode"] == "auto"
    assert eng_u.decode_variant == {"mode": "unfused",
                                    "block": "composed",
                                    "attn": "unfused",
                                    "mlp": "unfused"}


def test_engine_forced_pallas_smoke(params):
    """fused_decode='pallas' runs the actual megakernel decode program
    (interpret mode on CPU) end to end and names its program spec for
    the audit gate."""
    eng = _engine(params, capacity=2, prefill_buckets=(8,),
                  fused_decode="pallas")
    assert eng.decode_variant == {"mode": "pallas",
                                  "block": "composed",
                                  "attn": "pallas_fused",
                                  "mlp": "pallas_fused"}
    assert any(s.name == "serving_decode_fused"
               for s in eng.program_specs(register=False))
    rng = np.random.RandomState(8)
    rs = [eng.submit(rng.randint(0, 97, (6,)).astype(np.int32),
                     GenerationConfig(max_new_tokens=3, greedy=True))
          for _ in range(2)]
    eng.drain()
    assert all(r.done and len(r.tokens) == 3 for r in rs)
    assert eng.counters["decode_traces"] == 1


def test_engine_forced_block_smoke(params):
    """fused_decode='block' runs the single-launch decode program end
    to end (interpret mode on CPU), names the serving_decode_block spec
    for the audit gate, and its greedy tokens match the auto engine
    (the composed tier the block kernel is a roundoff variant of)."""
    eng = _engine(params, capacity=2, prefill_buckets=(8,),
                  fused_decode="block")
    assert eng.decode_variant == {"mode": "block",
                                  "block": "pallas_block",
                                  "attn": "pallas_block",
                                  "mlp": "pallas_block"}
    assert any(s.name == "serving_decode_block"
               for s in eng.program_specs(register=False))
    rng = np.random.RandomState(12)
    prompts = [rng.randint(0, 97, (6,)).astype(np.int32)
               for _ in range(2)]
    g = GenerationConfig(max_new_tokens=3, greedy=True)
    rs = [eng.submit(p, g) for p in prompts]
    eng.drain()
    assert all(r.done and len(r.tokens) == 3 for r in rs)
    assert eng.counters["decode_traces"] == 1
    eng_a = _engine(params, capacity=2, prefill_buckets=(8,))
    rs_a = [eng_a.submit(p, g) for p in prompts]
    eng_a.drain()
    assert [r.tokens for r in rs] == [r.tokens for r in rs_a]


def test_block_mode_is_single_device(params):
    """The single-launch kernel runs outside shard_map: a mesh engine
    pinned to 'block' is rejected at construction, and the TP decode
    body refuses the mode before tracing anything."""
    from paddle_tpu.inference import ServingMesh
    from paddle_tpu.inference import tp as tp_mod
    with pytest.raises(ValueError, match="single-device"):
        _engine(params, mesh=ServingMesh.make(tp=2),
                fused_decode="block")
    with pytest.raises(ValueError, match="single-device"):
        tp_mod._tp_decode_step(params, None, CFG, None, None, None,
                               None, fused="block")


def test_generate_paged_fused_flag_parity(params):
    rng = np.random.RandomState(9)
    prompts = jnp.asarray(rng.randint(0, 97, (2, 8)), jnp.int32)
    g = GenerationConfig(max_new_tokens=6, greedy=True)
    base = np.asarray(generate_paged(params, prompts, CFG, g,
                                     fused_decode=False))
    fused = np.asarray(generate_paged(params, prompts, CFG, g))
    np.testing.assert_array_equal(base, fused)
    # the forced single-launch route decodes the same greedy tokens
    # (roundoff-level logits variant of the composition)
    block = np.asarray(generate_paged(params, prompts, CFG, g,
                                      fused_decode="block"))
    np.testing.assert_array_equal(base, block)
    with pytest.raises(ValueError, match="fused_decode"):
        _fused_mode("bogus")
    assert _fused_mode(None) == "auto"       # flag defaults on
    assert _fused_mode(True) == "auto"
    assert _fused_mode(False) is False


# ---------------------------------------------------------------------------
# satellite: autotune-cache robustness
# ---------------------------------------------------------------------------
def test_autotune_cache_discards_corrupt_file(tmp_path):
    from paddle_tpu.ops.pallas.autotune import AutotuneCache
    p = tmp_path / "autotune.json"
    p.write_text('{"k": 1')                     # truncated write
    with pytest.warns(RuntimeWarning, match="corrupt autotune cache"):
        cache = AutotuneCache(str(p))
        assert cache.get("k") is None
    cache.put("k2", 3)                          # rewrites a clean cache
    assert json.loads(p.read_text()) == {"k2": 3}


def test_autotune_cache_discards_wrong_shape(tmp_path):
    from paddle_tpu.ops.pallas.autotune import AutotuneCache
    p = tmp_path / "autotune.json"
    p.write_text("[1, 2, 3]")                   # valid JSON, not a dict
    with pytest.warns(RuntimeWarning, match="corrupt autotune cache"):
        assert AutotuneCache(str(p)).get("k") is None


def test_autotune_cache_atomic_write(tmp_path):
    """put() must publish via temp + os.replace: the cache file is a
    complete JSON document at every point and no temp files leak."""
    from paddle_tpu.ops.pallas.autotune import AutotuneCache
    p = tmp_path / "autotune.json"
    cache = AutotuneCache(str(p))
    for i in range(5):
        cache.put(f"k{i}", i)
        assert json.loads(p.read_text()) == {f"k{j}": j
                                             for j in range(i + 1)}
    assert not list(tmp_path.glob("*.tmp"))
    fresh = AutotuneCache(str(p))               # round-trips
    assert fresh.get("k3") == 3


# ---------------------------------------------------------------------------
# satellite: per-kernel bench regression gate
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def gate():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "kernel_bench_gate.py")
    spec = importlib.util.spec_from_file_location("kernel_bench_gate",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bank(tmp, name, cases, wrap_parsed=False):
    doc = {"kernels": {"cases": cases}}
    if wrap_parsed:
        doc = {"parsed": doc}
    (tmp / name).write_text(json.dumps(doc))


def test_gate_flags_regression(gate, tmp_path):
    _bank(tmp_path, "BENCH_r01.json",
          {"k1": {"us_pallas": 100.0}, "k2": {"us_pallas": 50.0}})
    cap = {"kernels": {"cases": {"k1": {"us_pallas": 200.0},
                                 "k2": {"us_pallas": 55.0},
                                 "k3": {"us_pallas": 10.0}}}}
    res = gate.gate_capture(cap, threshold=0.30, repo=str(tmp_path))
    assert res["status"] == "regressed"
    assert set(res["regressions"]) == {"k1"}     # k2: +10% < threshold
    assert res["regressions"]["k1"]["ratio"] == 2.0
    assert res["new"] == ["k3"]
    assert res["checked"] == 2


def test_gate_best_across_trajectory_and_parsed_wrapper(gate, tmp_path):
    """The reference is the trajectory's MINIMUM, including captures
    wrapped under BENCH_rNN's 'parsed' key."""
    _bank(tmp_path, "BENCH_r01.json", {"k1": {"us_pallas": 100.0}})
    _bank(tmp_path, "BENCH_r02.json", {"k1": {"us_pallas": 80.0}},
          wrap_parsed=True)
    cap = {"kernels": {"cases": {"k1": {"us_pallas": 99.0}}}}
    res = gate.gate_capture(cap, threshold=0.2, repo=str(tmp_path))
    assert res["status"] == "regressed"          # 99 vs best 80 = 1.24x
    assert res["regressions"]["k1"]["banked_best"] == 80.0
    res = gate.gate_capture(cap, threshold=0.3, repo=str(tmp_path))
    assert res["status"] == "pass"


def test_gate_skips_without_reference(gate, tmp_path):
    cap = {"kernels": {"cases": {"k1": {"us_pallas": 10.0}}}}
    assert gate.gate_capture(cap, repo=str(tmp_path))["status"] == \
        "no_reference"
    _bank(tmp_path, "BENCH_r01.json", {"k1": {"us_pallas": 100.0}})
    interp = {"kernels": {"interpret": True,
                          "cases": {"k1": {"us_pallas": 900.0}}}}
    assert gate.gate_capture(interp, repo=str(tmp_path))["status"] == \
        "no_reference"                           # interpret: no timing


def test_gate_names_skipped_keys_instead_of_bare_pass(gate, tmp_path):
    """Trajectory files exist but share no kernel key with the capture:
    the gate must say exactly which keys it skipped (and exit 0 as a
    SKIP, not report a vacuous pass), and a partial overlap must list
    the banked keys the capture stopped timing."""
    _bank(tmp_path, "BENCH_r01.json", {"old_kernel": {"us_pallas": 50.0},
                                       "k1": {"us_pallas": 100.0}})
    cap = {"kernels": {"cases": {"renamed": {"us_pallas": 10.0}}}}
    res = gate.gate_capture(cap, repo=str(tmp_path))
    assert res["status"] == "no_reference"
    assert "k1" in res["note"] and "renamed" in res["note"]
    assert res["skipped_banked"] == ["k1", "old_kernel"]
    # partial overlap: gate runs, but the dropped key is named
    cap = {"kernels": {"cases": {"k1": {"us_pallas": 90.0}}}}
    res = gate.gate_capture(cap, repo=str(tmp_path))
    assert res["status"] == "pass" and res["checked"] == 1
    assert res["skipped_banked"] == ["old_kernel"]


def test_gate_cli_exit_codes(gate, tmp_path):
    _bank(tmp_path, "BENCH_r01.json", {"k1": {"us_pallas": 100.0}})
    cap = tmp_path / "fresh.json"
    out = tmp_path / "gate.json"
    cap.write_text(json.dumps(
        {"kernels": {"cases": {"k1": {"us_pallas": 300.0}}}}))
    rc = gate.main(["--capture", str(cap), "--repo", str(tmp_path),
                    "--json", str(out), "--quiet"])
    assert rc == 1
    assert json.loads(out.read_text())["status"] == "regressed"
    cap.write_text(json.dumps(
        {"kernels": {"cases": {"k1": {"us_pallas": 90.0}}}}))
    assert gate.main(["--capture", str(cap), "--repo", str(tmp_path),
                      "--quiet"]) == 0
    assert gate.main(["--quiet"]) == 3           # no --capture
    assert gate.main(["--capture", str(tmp_path / "missing.json"),
                      "--quiet"]) == 3
