"""Fused prefill-block megakernels (ops/pallas/fused_prefill_block.py):
ragged chunked prefill writing straight into the paged KV pools.

Contract under test:
- kernel-level parity (interpret mode, forced Pallas) vs the exact
  dense composition at the ragged edges — 1 valid row, all-full chunk,
  prime valid lengths, warm mid-page starts, int8 pools;
- registry dispatch/force/fallback + the VMEM-budget fallback with a
  readable reason string;
- engine-level: greedy output through FLAGS_fused_prefill (default ON)
  is BIT-identical to fused_prefill=False wherever dispatch falls back
  (which is everywhere on CPU) — cold AND prefix-cache warm, fp32 and
  int8 pools, colocated AND disaggregated engines; a forced-pallas
  engine keeps steady state at <=1 prefill program per bucket with
  zero retrace warnings over a 20+-request stream.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.models import llama
from paddle_tpu.inference import GenerationConfig, ServingEngine
from paddle_tpu.ops.pallas import fused_prefill_block as fpb
from paddle_tpu.ops.pallas.registry import KERNELS

pytestmark = pytest.mark.fused_prefill

CFG = llama.LlamaConfig(vocab_size=97, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        max_position_embeddings=160, dtype=jnp.float32,
                        remat=False)

_RNG = np.random.RandomState(11)


def _f32(*shape):
    return jnp.asarray(_RNG.randn(*shape) * 0.3, jnp.float32)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _kernel_inputs(P=16, D=32, H=4, KV=2, hd=16, BS=8, MB=6, pos0=0,
                   quant=False, seed=0):
    rng = np.random.RandomState(seed)
    f = lambda *s: jnp.asarray(rng.randn(*s) * 0.3, jnp.float32)  # noqa: E731
    N = MB + 3
    x, nw = f(P, D), jnp.abs(f(D)) + 0.5
    wq, wk, wv = f(D, H * hd), f(D, KV * hd), f(D, KV * hd)
    wo = f(H * hd, D)
    inv = 1.0 / (10000.0 ** (np.arange(0, hd, 2) / hd))
    ang = (pos0 + np.arange(P))[:, None] * inv[None, :]
    sin = jnp.asarray(np.sin(ang), jnp.float32)
    cos = jnp.asarray(np.cos(ang), jnp.float32)
    if quant:
        kp = jnp.asarray(rng.randint(-127, 127, (N, BS, KV, hd)),
                         jnp.int8)
        vp = jnp.asarray(rng.randint(-127, 127, (N, BS, KV, hd)),
                         jnp.int8)
        sc = (jnp.abs(f(KV)) * 0.05 + 0.01,
              jnp.abs(f(KV)) * 0.05 + 0.01)
    else:
        kp, vp = f(N, BS, KV, hd), f(N, BS, KV, hd)
        sc = None
    tab = jnp.asarray(rng.permutation(N - 1)[:MB] + 1, jnp.int32)
    return (x, nw, wq, wk, wv, wo, sin, cos, kp, vp, tab), sc


def _compare(args, sc, pos0, n_valid, tol=1e-4):
    ref = fpb.prefill_attn_block_ref(*args, jnp.int32(pos0),
                                     jnp.int32(n_valid), sc)
    with KERNELS.force("prefill_attn_block", "pallas_fused"):
        got = jax.jit(
            lambda *a: fpb.fused_prefill_attn_pallas(*a, kv_scales=sc)
        )(*args, jnp.int32(pos0), jnp.int32(n_valid))
    for name, g, r in zip(("xo", "kn", "vn"), got, ref):
        ga, ra = np.asarray(g), np.asarray(r)
        if name == "xo":
            # rows past n_valid are unspecified (their compute is
            # skipped — the ragged contract); compare the live rows
            ga, ra = ga[:n_valid], ra[:n_valid]
        np.testing.assert_allclose(ga, ra, rtol=tol, atol=tol,
                                   err_msg=name)


# -- kernel parity at the ragged edges ---------------------------------

@pytest.mark.parametrize("pos0,n_valid", [
    (0, 16),      # cold, all-full chunk
    (0, 1),       # 1 valid row (the minimum suffix)
    (0, 13),      # prime valid length, cold
    (10, 13),     # warm mid-page start (COW-fork tail territory)
    (29, 7),      # warm start late in the window, prime remainder
    (8, 16),      # page-aligned warm start, full chunk
])
def test_kernel_parity_ragged_edges_fp32(pos0, n_valid):
    args, sc = _kernel_inputs(pos0=pos0, seed=pos0 * 31 + n_valid)
    _compare(args, sc, pos0, n_valid)


def test_kernel_parity_int8_pool(params):
    args, sc = _kernel_inputs(pos0=10, quant=True, seed=5)
    _compare(args, sc, 10, 13, tol=2e-4)


def test_kernel_parity_wide_chunk_multiple_q_blocks():
    """P=32 with block_q=16 forced: two q blocks, the second partially
    valid — the per-block online-softmax state must reset per block."""
    args, sc = _kernel_inputs(P=32, MB=8, pos0=16, seed=9)
    ref = fpb.prefill_attn_block_ref(*args, jnp.int32(16),
                                     jnp.int32(19), sc)
    got = fpb.fused_prefill_attn_pallas(*args, jnp.int32(16),
                                        jnp.int32(19), block_q=16,
                                        pages_per_step=2)
    np.testing.assert_allclose(np.asarray(got[0])[:19],
                               np.asarray(ref[0])[:19],
                               rtol=1e-4, atol=1e-4)


def test_kernel_rejects_non_divisor_block_q():
    args, sc = _kernel_inputs(P=16, seed=3)
    with pytest.raises(ValueError, match="block_q"):
        fpb.fused_prefill_attn_pallas(*args, jnp.int32(0),
                                      jnp.int32(16), block_q=5,
                                      pages_per_step=1)


def test_chunk_pool_write_redirects_pad_and_shared_pages():
    """write_chunk_to_pool: valid rows land at their positions through
    the WRITE table; pad rows and shared (redirected) pages land in
    scratch page 0 — a shared page's bytes never change."""
    from paddle_tpu.ops.paged_attention import write_chunk_to_pool
    L_BS, KV, hd, MB = 8, 2, 16, 4
    kp = jnp.zeros((9, L_BS, KV, hd), jnp.float32)
    vp = jnp.zeros_like(kp)
    wtable = jnp.asarray([0, 3, 5, 7], jnp.int32)   # page 0 = shared
    kn = jnp.ones((16, KV, hd), jnp.float32)
    vn = jnp.full((16, KV, hd), 2.0, jnp.float32)
    # pos0=8 -> logical pages 1..2; n_valid=10 -> 6 pad rows
    kp2, vp2 = write_chunk_to_pool(kp, vp, wtable, 8, 10, kn, vn)
    kp2 = np.asarray(kp2)
    assert np.all(kp2[3, :8] == 1.0)            # page 1 fully written
    assert np.all(kp2[5, 0:2] == 1.0)           # first 2 rows of page 2
    assert np.all(kp2[5, 2:] == 0.0)            # pad rows NOT here
    assert np.all(kp2[7] == 0.0)                # untouched page
    assert np.all(np.asarray(vp2)[3, :8] == 2.0)


# -- registry dispatch --------------------------------------------------

def test_dispatch_falls_back_under_interpret_with_reason():
    meta = fpb.prefill_meta_dims(32, 64, 4, 2, 16, 128, 8, 8,
                                 jnp.float32, jnp.float32, False)
    meta["interpret"] = True
    rows = KERNELS.explain("prefill_attn_block", meta)
    sel = [r for r in rows if r["selected"]]
    assert sel and sel[0]["name"] == "unfused"
    assert all(isinstance(r["reason"], str) and r["reason"]
               for r in rows)


def test_dispatch_vmem_budget_fallback():
    """A bucket whose weights + scratch exceed the budget falls back
    with the budget named; a generous budget admits it."""
    meta = fpb.prefill_meta_dims(128, 1024, 16, 16, 64, 4096, 16, 24,
                                 jnp.bfloat16, jnp.bfloat16, False)
    meta["interpret"] = False
    meta["vmem_budget"] = 1 << 20          # 1 MiB: nothing fits
    ok, why = fpb._supports_prefill_attn(meta)
    assert not ok and "VMEM" in why
    meta["vmem_budget"] = 64 << 20
    ok, why = fpb._supports_prefill_attn(meta)
    assert ok, why


def test_dispatch_rejects_bad_head_dim_and_ragged_bucket():
    meta = fpb.prefill_meta_dims(32, 40, 2, 2, 20, 96, 8, 8,
                                 jnp.float32, jnp.float32, False)
    meta["interpret"] = False
    ok, why = fpb._supports_prefill_attn(meta)
    assert not ok and "head_dim" in why
    meta2 = fpb.prefill_meta_dims(13, 64, 4, 2, 16, 128, 8, 8,
                                  jnp.float32, jnp.float32, False)
    meta2["interpret"] = False
    ok, why = fpb._supports_prefill_attn(meta2)
    assert not ok and "P=13" in why


def test_resolve_modes_and_selected_gate():
    meta = fpb.prefill_meta_dims(16, 32, 4, 2, 16, 64, 8, 6,
                                 jnp.float32, jnp.float32, False)
    _, _, names = fpb.resolve_prefill_blocks(meta, "pallas")
    assert names == {"attn": "pallas_fused", "mlp": "pallas_fused"}
    _, _, names = fpb.resolve_prefill_blocks(meta, "ref")
    assert names == {"attn": "unfused", "mlp": "unfused"}
    with pytest.raises(ValueError):
        fpb.resolve_prefill_blocks(meta, "nope")
    # on CPU (interpret) auto dispatch rejects -> fused chunk not built
    assert not fpb.prefill_fused_selected(meta, "auto")
    assert fpb.prefill_fused_selected(meta, "pallas")
    assert not fpb.prefill_fused_selected(meta, False)


# -- engine integration -------------------------------------------------

def _stream(eng, n=8, seed=3, max_new=6, lens=(4, 40)):
    rng = np.random.RandomState(seed)
    reqs = [eng.submit(rng.randint(0, 97, (int(s),)).astype(np.int32),
                       GenerationConfig(max_new_tokens=max_new,
                                        greedy=True))
            for s in rng.randint(lens[0], lens[1], n)]
    eng.drain()
    return [r.output_ids for r in reqs]


def _engine(params, **kw):
    kw.setdefault("capacity", 2)
    kw.setdefault("block_size", 8)
    kw.setdefault("prefill_buckets", (16, 32))
    kw.setdefault("max_seq_len", 96)
    return ServingEngine(params, CFG, **kw)


def test_engine_default_flag_bit_identical_to_unfused(params):
    """FLAGS_fused_prefill default ON: on CPU dispatch falls back to
    the VERBATIM unfused chunk — greedy output is bit-identical to an
    explicitly-unfused engine, and the variant report says so."""
    a = _engine(params)
    b = _engine(params, fused_prefill=False)
    outs_a, outs_b = _stream(a), _stream(b)
    assert all(np.array_equal(x, y) for x, y in zip(outs_a, outs_b))
    assert a.prefill_variant["attn"] == "unfused"
    assert a.metrics()["prefill_variant"]["mode"] == "auto"
    assert b.prefill_variant == {"mode": "unfused", "attn": "unfused",
                                 "mlp": "unfused"}


def test_engine_prefix_cache_warm_bit_identical(params):
    """Warm suffix prefill over shared prefix pages: default-flag
    engine vs unfused engine, bit-identical outputs AND identical
    prefix-cache hit accounting."""
    rng = np.random.RandomState(9)
    sysp = rng.randint(0, 97, (24,)).astype(np.int32)
    prompts = [np.concatenate([sysp, rng.randint(0, 97, (5 + i,))])
               .astype(np.int32) for i in range(4)]

    def run(fp):
        eng = _engine(params, prefix_cache=True, num_blocks=64,
                      fused_prefill=fp)
        outs = []
        for p in prompts:
            r = eng.submit(p, GenerationConfig(max_new_tokens=5,
                                               greedy=True))
            eng.drain()
            outs.append(r.output_ids)
        return outs, eng._pcache.stats["tokens_skipped"]

    oa, skip_a = run(None)
    ob, skip_b = run(False)
    assert all(np.array_equal(x, y) for x, y in zip(oa, ob))
    assert skip_a == skip_b > 0


@pytest.mark.parametrize("cache_dtype", [None, "int8"])
def test_engine_forced_pallas_stream_token_parity(params, cache_dtype):
    """A forced-pallas engine (interpret mode) over a 20+-request
    mixed-arrival stream: greedy token parity with the unfused engine,
    <=1 prefill program per bucket, 1 decode program, zero retrace
    warnings."""
    ref = _engine(params, capacity=3, cache_dtype=cache_dtype,
                  fused_prefill=False)
    eng = _engine(params, capacity=3, cache_dtype=cache_dtype,
                  fused_prefill="pallas", observability=True)
    # warm both buckets + the decode program outside the watched window
    rng = np.random.RandomState(4)
    for s in (10, 20):
        eng.submit(rng.randint(0, 97, (s,)).astype(np.int32),
                   GenerationConfig(max_new_tokens=2, greedy=True))
    eng.drain()
    eng.reset_metrics()                     # arms the retrace watchdog
    outs_ref = _stream(ref, n=22, seed=13)
    outs = _stream(eng, n=22, seed=13)
    match = sum(bool(np.array_equal(a, b))
                for a, b in zip(outs, outs_ref))
    # interpret-mode Pallas vs the composition is roundoff-parity;
    # greedy argmax absorbs it in fp32 — but int8 pool writes ROUND
    # (round(x/s) is discontinuous), so a ~1e-6 perturbation can flip
    # a quantized cell and cascade through greedy decode: allow a
    # couple of boundary flips there, exact elsewhere
    floor = len(outs) if cache_dtype is None else len(outs) - 2
    assert match >= floor, f"{match}/{len(outs)} matched"
    m = eng.metrics()
    assert m["retrace_warnings"] == 0
    assert all(v == 1 for v in m["prefill_traces"].values()), \
        m["prefill_traces"]
    assert m["decode_traces"] == 1
    assert m["prefill_variant"] == {"mode": "pallas",
                                    "attn": "pallas_fused",
                                    "mlp": "pallas_fused"}
    assert m["prefill_pad_tokens"] > 0       # ragged chunks occurred


def test_engine_program_cache_keys_the_pin_route(params):
    """A chunk program traced under a KERNELS.force pin must not be
    replayed for unpinned calls: the per-bucket cache keys the route."""
    eng = _engine(params)
    outs1 = _stream(eng, n=2, seed=1)
    n_keys = len(eng._prefill_fns)
    with KERNELS.force("prefill_attn_block", "pallas_fused"), \
            KERNELS.force("prefill_mlp_block", "pallas_fused"):
        _stream(eng, n=2, seed=2)
    assert len(eng._prefill_fns) > n_keys    # distinct route entries
    outs3 = _stream(eng, n=2, seed=1)
    ref = _engine(params, fused_prefill=False)
    assert all(np.array_equal(a, b)
               for a, b in zip(outs3, _stream(ref, n=2, seed=1)))
    assert all(np.array_equal(a, b) for a, b in zip(outs1, outs3))


def test_engine_pallas_pin_rejected_on_tp_mesh(params):
    from paddle_tpu.inference import ServingMesh
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    mesh = ServingMesh.make(tp=2, collective="psum")
    with pytest.raises(ValueError, match="fused_prefill"):
        _engine(params, mesh=mesh, fused_prefill="pallas")
    # auto mode on a tp>1 mesh quietly keeps the unfused chunk
    eng = _engine(params, mesh=mesh)
    assert eng.prefill_variant["attn"] == "unfused"


def test_disagg_engine_parity_with_colocated(params):
    """Disaggregated engine with the default fused_prefill flag vs the
    colocated unfused engine: greedy output bit-identical (CPU
    dispatch falls back on both, so the flag must not perturb the
    handoff path)."""
    from paddle_tpu.inference.disagg import DisaggregatedEngine
    ref = _engine(params, capacity=2, fused_prefill=False)
    devs = jax.devices()
    eng = DisaggregatedEngine(params, CFG, capacity=2, prefill_slots=1,
                              prefill_devices=devs[:1],
                              decode_devices=devs[1:2] or devs[:1],
                              block_size=8, max_seq_len=96,
                              prefill_buckets=(16, 32))
    outs_ref = _stream(ref, n=6, seed=21)
    rng = np.random.RandomState(21)
    reqs = [eng.submit(rng.randint(0, 97, (int(s),)).astype(np.int32),
                       GenerationConfig(max_new_tokens=6, greedy=True))
            for s in rng.randint(4, 40, 6)]
    eng.drain()
    outs = [r.output_ids for r in reqs]
    assert all(np.array_equal(a, b) for a, b in zip(outs, outs_ref))


def test_generate_paged_prefix_store_fused_matches(params):
    """generate_paged(prefix_cache=store, fused_prefill=...): forced
    pallas (interpret) matches the unfused suffix path token-for-token
    on cold AND warm calls."""
    from paddle_tpu.inference.generation import generate_paged
    from paddle_tpu.inference.prefix_cache import PagedKVCacheStore
    rng = np.random.RandomState(2)
    toks = jnp.asarray(rng.randint(0, 97, (1, 20)), jnp.int32)
    toks2 = jnp.asarray(
        np.concatenate([np.asarray(toks)[:, :16],
                        rng.randint(0, 97, (1, 8))], axis=1), jnp.int32)
    g = GenerationConfig(max_new_tokens=5, greedy=True)

    def run(fp):
        store = PagedKVCacheStore(CFG, block_size=8, num_blocks=64)
        a = np.asarray(generate_paged(params, toks, CFG, g,
                                      block_size=8, prefix_cache=store,
                                      fused_prefill=fp))
        b = np.asarray(generate_paged(params, toks2, CFG, g,
                                      block_size=8, prefix_cache=store,
                                      fused_prefill=fp))
        return a, b

    a0, b0 = run(False)
    a1, b1 = run("pallas")
    assert np.array_equal(a0, a1) and np.array_equal(b0, b1)


def test_fused_prefill_audit_spec_is_clean(params):
    """A forced-pallas-prefill engine's bucket program audits clean
    (the serving_prefill_fused catalog entry's contract)."""
    from paddle_tpu.analysis import audit_spec
    eng = _engine(params, prefill_buckets=(16,),
                  fused_prefill="pallas")
    specs = [s for s in eng.program_specs(register=False)
             if s.name.startswith("serving_prefill_fused")]
    assert len(specs) == 1
    rep = audit_spec(specs[0])
    bad = [f for f in rep.findings if f.severity != "info"]
    assert not bad, [f.to_dict() for f in bad]
