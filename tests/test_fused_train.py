"""Fused training-path kernels (ops/pallas/fused_train.py + the
RMSNorm backward / residual+norm epilogue in ops/pallas/norms.py).

Parity contract: wherever registry dispatch selects the ``unfused``
composition (always on CPU/interpret, or with ``fused_train="ref"``),
the training path is BIT-identical to the pre-fusion code — asserted
exactly. The Pallas kernels themselves (pinned, interpret mode) match
``jax.grad`` of the unfused composition to fp32 roundoff across
randomized shapes (documented tolerance: 1e-5 abs in fp32, 2e-2 in
bf16 — both paths accumulate in f32, the difference is reduction
order + the low-precision output cast).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.models import gpt, llama
from paddle_tpu.models._common import (fused_linear_cross_entropy,
                                       masked_cross_entropy)
from paddle_tpu.ops.pallas import fused_train as ft
from paddle_tpu.ops.pallas import norms
from paddle_tpu.ops.pallas._util import fused_train_mode
from paddle_tpu.ops.pallas.registry import KERNELS

pytestmark = pytest.mark.fused_train

CFG = llama.LlamaConfig(vocab_size=97, hidden_size=32,
                        intermediate_size=64, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        max_position_embeddings=32, dtype=jnp.float32,
                        remat=False)


def _labels(rng, shape, v, ignore_frac=0.25):
    """Labels with a mix of valid ids and BOTH negative ignore
    conventions (-1 and -100)."""
    lab = rng.randint(0, v, shape).astype(np.int32)
    drop = rng.rand(*shape) < ignore_frac
    lab[drop] = np.where(rng.rand(int(drop.sum())) < 0.5, -1, -100)
    return jnp.asarray(lab)


# ---------------------------------------------------------------------------
# fused linear + cross entropy: loss AND grad parity, randomized shapes
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_linear_ce_parity_randomized_fp32(seed):
    rng = np.random.RandomState(seed)
    t = int(rng.randint(19, 70))            # never a block multiple
    d = int(rng.choice([16, 32, 48]))
    v = int(rng.choice([33, 97, 131]))      # never a lane multiple
    x = jnp.asarray(rng.randn(t, d) * 0.3, jnp.float32)
    head = jnp.asarray(rng.randn(d, v) * 0.1, jnp.float32)
    lab = _labels(rng, (t,), v)
    bt = int(rng.choice([8, 16]))
    bv = int(rng.choice([128, 256]))

    lp, (dxp, dhp) = jax.value_and_grad(
        lambda a, h: ft.linear_ce_pallas(a, h, lab, block_t=bt,
                                         block_v=bv),
        argnums=(0, 1))(x, head)
    lr, (dxr, dhr) = jax.value_and_grad(
        lambda a, h: ft.linear_ce_ref(a, h, lab), argnums=(0, 1))(x, head)
    assert abs(float(lp) - float(lr)) < 1e-5
    np.testing.assert_allclose(np.asarray(dxp), np.asarray(dxr),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dhp), np.asarray(dhr),
                               atol=1e-5, rtol=1e-5)
    # and vs the UNCHUNKED definition (full-logits masked CE)
    lm = masked_cross_entropy((x @ head)[None], lab[None])
    assert abs(float(lp) - float(lm)) < 1e-5


def test_linear_ce_parity_bf16_params_fp32_master():
    """bf16 params / fp32 interior (the mixed-precision trainer
    layout): the kernel's f32 logit tiles + f32 accumulators must match
    the scan composition (also f32 interior) to bf16-cast roundoff."""
    rng = np.random.RandomState(3)
    t, d, v = 53, 32, 97
    x = jnp.asarray(rng.randn(t, d) * 0.3, jnp.bfloat16)
    head = jnp.asarray(rng.randn(d, v) * 0.1, jnp.bfloat16)
    lab = _labels(rng, (t,), v)
    lp, (dxp, dhp) = jax.value_and_grad(
        lambda a, h: ft.linear_ce_pallas(a, h, lab, block_t=16,
                                         block_v=128),
        argnums=(0, 1))(x, head)
    lr, (dxr, dhr) = jax.value_and_grad(
        lambda a, h: ft.linear_ce_ref(a, h, lab), argnums=(0, 1))(x, head)
    assert lp.dtype == jnp.float32          # loss stays f32
    assert dxp.dtype == jnp.bfloat16 and dhp.dtype == jnp.bfloat16
    assert abs(float(lp) - float(lr)) < 2e-3
    np.testing.assert_allclose(np.asarray(dxp, np.float32),
                               np.asarray(dxr, np.float32),
                               atol=2e-2, rtol=2e-2)
    np.testing.assert_allclose(np.asarray(dhp, np.float32),
                               np.asarray(dhr, np.float32),
                               atol=2e-2, rtol=2e-2)


def test_linear_ce_leading_batch_and_ragged_chunks():
    """[B, S, D] hidden with T=B*S not divisible by block_t: padding
    tokens enter as label -1 and must not contribute."""
    rng = np.random.RandomState(4)
    b, s, d, v = 3, 11, 16, 33               # T = 33, blocks of 8
    x = jnp.asarray(rng.randn(b, s, d) * 0.3, jnp.float32)
    head = jnp.asarray(rng.randn(d, v) * 0.1, jnp.float32)
    lab = _labels(rng, (b, s), v)
    lp = ft.linear_ce_pallas(x, head, lab, block_t=8, block_v=128)
    lr = ft.linear_ce_ref(x, head, lab)
    assert abs(float(lp) - float(lr)) < 1e-5


def test_linear_ce_all_labels_ignored():
    """count == 0: the masked mean's max(count, 1) guard — loss 0,
    grads 0, no NaN from 0/0."""
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(24, 16) * 0.3, jnp.float32)
    head = jnp.asarray(rng.randn(16, 33) * 0.1, jnp.float32)
    lab = jnp.full((24,), -100, jnp.int32)
    loss, (dx, dh) = jax.value_and_grad(
        lambda a, h: ft.linear_ce_pallas(a, h, lab, block_t=8,
                                         block_v=128),
        argnums=(0, 1))(x, head)
    assert float(loss) == 0.0
    assert float(jnp.abs(dx).max()) == 0.0
    assert float(jnp.abs(dh).max()) == 0.0


# ---------------------------------------------------------------------------
# fused SwiGLU + RMSNorm backward + residual epilogue
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-5),
                                       (jnp.bfloat16, 2e-2)])
def test_swiglu_parity(dtype, tol):
    rng = np.random.RandomState(6)
    g = jnp.asarray(rng.randn(3, 13, 96), dtype)   # ragged rows
    u = jnp.asarray(rng.randn(3, 13, 96), dtype)

    def loss_p(a, b):
        return ft.swiglu_pallas(a, b, block_f=48).astype(
            jnp.float32).sum()

    def loss_r(a, b):
        return ft.swiglu_ref(a, b).astype(jnp.float32).sum()

    sp, gp = jax.value_and_grad(loss_p, argnums=(0, 1))(g, u)
    sr, gr = jax.value_and_grad(loss_r, argnums=(0, 1))(g, u)
    assert abs(float(sp) - float(sr)) < max(tol * 100, 1e-4)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=tol, rtol=tol)


def test_swiglu_rejects_non_divisor_block():
    g = jnp.zeros((4, 96), jnp.float32)
    with pytest.raises(ValueError, match="divide"):
        ft.swiglu_pallas(g, g, block_f=40)


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-5),
                                       (jnp.bfloat16, 2e-2)])
def test_rms_norm_bwd_parity(dtype, tol):
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(19, 64), dtype)
    w = jnp.asarray(rng.rand(64) + 0.5, dtype)
    g = jnp.asarray(rng.randn(19, 64), dtype)
    dxp, dwp = norms.rms_norm_bwd_pallas(x, w, g)
    dxr, dwr = norms._rms_bwd_ref(1e-6, (x, w), g)
    assert dxp.dtype == x.dtype and dwp.dtype == w.dtype
    np.testing.assert_allclose(np.asarray(dxp, np.float32),
                               np.asarray(dxr, np.float32),
                               atol=tol, rtol=tol)
    np.testing.assert_allclose(np.asarray(dwp, np.float32),
                               np.asarray(dwr, np.float32),
                               atol=max(tol, 1e-4), rtol=tol)


def test_residual_rms_norm_fwd_and_grad_parity():
    rng = np.random.RandomState(8)
    d = jnp.asarray(rng.randn(2, 9, 32) * 0.3, jnp.float32)
    x = jnp.asarray(rng.randn(2, 9, 32), jnp.float32)
    w = jnp.asarray(rng.rand(32) + 0.5, jnp.float32)

    yp, hp = norms.residual_rms_norm_pallas(d, x, w)
    yr, hr = norms.residual_rms_norm_ref(d, x, w)
    np.testing.assert_allclose(np.asarray(yp), np.asarray(yr),
                               atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(hp), np.asarray(hr),
                               atol=1e-5, rtol=1e-5)

    def loss(fn, dd, xx, ww):
        y, h = fn(dd, xx, ww)
        return (y * y).astype(jnp.float32).sum() \
            + (h * h).astype(jnp.float32).sum()

    gp = jax.grad(lambda *a: loss(norms.residual_rms_norm_pallas, *a),
                  argnums=(0, 1, 2))(d, x, w)
    gr = jax.grad(lambda *a: loss(norms.residual_rms_norm_ref, *a),
                  argnums=(0, 1, 2))(d, x, w)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------------
# registry dispatch / fallback + mode plumbing
# ---------------------------------------------------------------------------
def test_mode_normalization():
    assert fused_train_mode("ref") == "ref"
    assert fused_train_mode(False) == "ref"
    assert fused_train_mode(0) == "ref"
    assert fused_train_mode("pallas") == "pallas"
    assert fused_train_mode("force") == "pallas"
    assert fused_train_mode(True) == "auto"
    assert fused_train_mode("auto") == "auto"
    assert fused_train_mode(None) == "auto"   # FLAGS default on
    with pytest.raises(ValueError, match="auto|pallas|ref"):
        fused_train_mode("bogus")


def test_dispatch_falls_back_on_interpret_and_vmem():
    # interpret mode (this CPU run) -> composition for every op
    for op, meta in [
        ("fused_linear_ce", ft.ce_meta(64, 32, 128, jnp.float32)),
        ("fused_swiglu", ft.swiglu_meta(64, 128, jnp.float32)),
        ("rms_norm_bwd", norms.rms_bwd_meta(64, 32, jnp.float32)),
        ("rms_norm_residual", norms.rms_bwd_meta(64, 32, jnp.float32)),
    ]:
        assert meta["interpret"]
        name, _ = KERNELS.dispatch(op, meta)
        assert name == "unfused", op
    # off-interpret: the Pallas variant is selected...
    m = ft.ce_meta(4096, 2048, 32000, jnp.bfloat16)
    m["interpret"] = False
    assert KERNELS.dispatch("fused_linear_ce", m)[0] == "pallas_fused"
    # ...unless NO (block_t, block_v) tile fits the VMEM budget
    m = ft.ce_meta(4096, 1 << 20, 32000, jnp.float32)
    m["interpret"] = False
    name, _ = KERNELS.dispatch("fused_linear_ce", m)
    assert name == "unfused"
    exp = {e["name"]: e for e in KERNELS.explain("fused_linear_ce", m)}
    assert not exp["pallas_fused"]["supported"]
    assert "VMEM" in exp["pallas_fused"]["reason"]


def test_ref_mode_bit_identical_to_prefusion_composition():
    """The fallback CONTRACT: mode="ref" (and auto-dispatch on CPU) is
    the exact pre-fusion code, so outputs are bit-identical."""
    rng = np.random.RandomState(9)
    x = jnp.asarray(rng.randn(4, 9, 16) * 0.3, jnp.float32)
    head = jnp.asarray(rng.randn(16, 33) * 0.1, jnp.float32)
    lab = _labels(rng, (4, 9), 33)
    got = ft.fused_linear_ce(x, head, lab, mode="ref")
    want = fused_linear_cross_entropy(x, head, lab)
    assert np.asarray(got).tobytes() == np.asarray(want).tobytes()
    # auto-dispatch on CPU (interpret) routes to the same function
    auto = ft.fused_linear_ce(x, head, lab, mode="auto")
    assert np.asarray(auto).tobytes() == np.asarray(want).tobytes()

    g = jnp.asarray(rng.randn(4, 16), jnp.float32)
    u = jnp.asarray(rng.randn(4, 16), jnp.float32)
    assert np.asarray(ft.fused_swiglu(g, u, mode="ref")).tobytes() == \
        np.asarray(jax.nn.silu(g) * u).tobytes()

    d = jnp.asarray(rng.randn(4, 16) * 0.3, jnp.float32)
    w = jnp.asarray(rng.rand(16) + 0.5, jnp.float32)
    yp, hp = norms.residual_rms_norm(d, x[0, :4], w, mode="ref")
    yr, hr = norms.residual_rms_norm_ref(d, x[0, :4], w)
    assert np.asarray(yp).tobytes() == np.asarray(yr).tobytes()
    assert np.asarray(hp).tobytes() == np.asarray(hr).tobytes()


def test_rms_mode_pin_reaches_backward():
    """The call-site mode (a model's cfg.fused_train) must select the
    RMSNorm BACKWARD variant — not the global flag. The Pallas kernel
    and the jnp composition differ in low bits, so bitwise equality
    against each implementation discriminates the dispatched route."""
    rng = np.random.RandomState(15)
    x = jnp.asarray(rng.randn(9, 64), jnp.float32)
    w = jnp.asarray(rng.rand(64) + 0.5, jnp.float32)
    ct = jnp.asarray(rng.randn(9, 64), jnp.float32)

    def gx(mode):
        _, vjp = jax.vjp(
            lambda xx: norms.rms_norm_pallas(xx, w, 1e-6, mode), x)
        return np.asarray(vjp(ct)[0])

    dx_pallas = np.asarray(norms.rms_norm_bwd_pallas(x, w, ct)[0])
    dx_ref = np.asarray(norms._rms_bwd_ref(1e-6, (x, w), ct)[0])
    assert gx("pallas").tobytes() == dx_pallas.tobytes()
    assert gx("ref").tobytes() == dx_ref.tobytes()
    # the discriminator is real: the two routes differ somewhere
    assert dx_pallas.tobytes() != dx_ref.tobytes()


def test_residual_epilogue_mode_reaches_norm_backward():
    """residual_rms_norm's backward runs the norm backward through the
    SAME mode the epilogue was called with (the bug class: a pinned
    model whose epilogue backward silently followed the global flag)."""
    rng = np.random.RandomState(16)
    d = jnp.asarray(rng.randn(7, 64) * 0.3, jnp.float32)
    x = jnp.asarray(rng.randn(7, 64), jnp.float32)
    w = jnp.asarray(rng.rand(64) + 0.5, jnp.float32)

    def grads(mode):
        def loss(dd, xx):
            y, h = norms.residual_rms_norm_pallas(dd, xx, w, 1e-6, mode)
            return (y * y).sum() + (h * h).sum()
        return jax.grad(loss, argnums=(0, 1))(d, x)

    y, h = norms.residual_rms_norm_pallas(d, x, w, 1e-6, "pallas")
    for mode, bwd in (("pallas",
                       lambda: norms.rms_norm_bwd_pallas(y, w, 2 * h)),
                      ("ref",
                       lambda: norms._rms_bwd_ref(1e-6, (y, w), 2 * h))):
        dn, _ = bwd()
        want = np.asarray(dn + 2 * y)
        gd, gxx = grads(mode)
        assert np.asarray(gd).tobytes() == want.tobytes(), mode
        assert np.asarray(gxx).tobytes() == want.tobytes(), mode


def test_registry_force_pins_rms_bwd():
    """KERNELS.force routes the auto-dispatched RMSNorm backward onto
    the Pallas kernel even on CPU (the audit-catalog idiom)."""
    rng = np.random.RandomState(10)
    x = jnp.asarray(rng.randn(9, 32), jnp.float32)
    w = jnp.asarray(rng.rand(32) + 0.5, jnp.float32)

    def loss(xx):
        from paddle_tpu.ops import rms_norm
        return (rms_norm(xx, w) ** 2).sum()

    base = jax.grad(loss)(x)
    with KERNELS.force("rms_norm_bwd", "pallas_fused"):
        assert KERNELS.forced_state() == (("rms_norm_bwd",
                                           "pallas_fused"),)
        pinned = jax.grad(loss)(x)
    np.testing.assert_allclose(np.asarray(pinned), np.asarray(base),
                               atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# model-level: llama + gpt losses through the fused path
# ---------------------------------------------------------------------------
def test_llama_loss_and_grads_pallas_vs_ref():
    import dataclasses
    params = llama.init_params(CFG, jax.random.key(0),
                               dtype=jnp.float32)
    rng = np.random.RandomState(11)
    toks = jnp.asarray(rng.randint(0, 97, (2, 8)), jnp.int32)
    lab = _labels(rng, (2, 8), 97)
    cfg_p = dataclasses.replace(CFG, fused_train="pallas")
    cfg_r = dataclasses.replace(CFG, fused_train="ref")
    lp, gp = jax.value_and_grad(
        lambda p: llama.loss_fn(p, toks, lab, cfg_p))(params)
    lr, gr = jax.value_and_grad(
        lambda p: llama.loss_fn(p, toks, lab, cfg_r))(params)
    assert abs(float(lp) - float(lr)) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(gp),
                    jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4)


def test_gpt_loss_fused_vs_full_logits():
    """The gpt satellite: loss_fn no longer materializes [B, S, V] —
    semantics must match the old masked_cross_entropy(forward())."""
    cfg = gpt.GPTConfig(vocab_size=97, hidden_size=32,
                        intermediate_size=64, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=32,
                        dtype=jnp.float32, remat=False)
    params = gpt.init_params(cfg, jax.random.key(1))
    rng = np.random.RandomState(12)
    toks = jnp.asarray(rng.randint(0, 97, (2, 10)), jnp.int32)
    lab = _labels(rng, (2, 10), 97)
    got = gpt.loss_fn(params, toks, lab, cfg)
    want = masked_cross_entropy(gpt.forward(params, toks, cfg), lab)
    assert abs(float(got) - float(want)) < 1e-5
    # grads flow through the tied embedding both ways
    g = jax.grad(lambda p: gpt.loss_fn(p, toks, lab, cfg))(params)
    gw = jax.grad(lambda p: masked_cross_entropy(
        gpt.forward(p, toks, cfg), lab))(params)
    np.testing.assert_allclose(np.asarray(g["wte"]),
                               np.asarray(gw["wte"]),
                               atol=5e-5, rtol=5e-4)


# ---------------------------------------------------------------------------
# trainer: 10-step loss-trajectory parity, exactly one compile
# ---------------------------------------------------------------------------
def _trainer(cfg, **kw):
    from paddle_tpu.distributed.trainer import (MeshConfig, Trainer,
                                                make_mesh)
    mesh = make_mesh(MeshConfig(), devices=jax.devices()[:1])
    kw.setdefault("data_spec", P())
    kw.setdefault("lr", 1e-3)
    return Trainer(lambda p, t, l: llama.loss_fn(p, t, l, cfg), mesh,
                   llama.param_shardings(mesh, cfg), **kw)


def _run_traj(cfg, steps=10):
    tr = _trainer(cfg, observability=True)
    state = tr.init_state(llama.init_params(CFG, jax.random.key(0),
                                            dtype=jnp.float32))
    rng = np.random.RandomState(13)
    toks = jnp.asarray(rng.randint(0, 97, (2, 8)), jnp.int32)
    lab = jnp.asarray(np.roll(np.asarray(toks), -1, -1))
    losses = []
    for _ in range(steps):
        state, m = tr.step(state, toks, lab)
        losses.append(float(m["loss"]))
    return losses, tr.metrics()["compiles"]


def test_trainer_10_step_trajectory_parity_one_compile():
    import dataclasses
    loss_p, compiles_p = _run_traj(
        dataclasses.replace(CFG, fused_train="pallas"))
    loss_r, compiles_r = _run_traj(
        dataclasses.replace(CFG, fused_train="ref"))
    assert compiles_p == 1, "fused trainer must compile exactly once"
    assert compiles_r == 1
    assert all(np.isfinite(loss_p))
    # documented tolerance: per-step fp32 roundoff compounds through
    # 10 optimizer updates
    np.testing.assert_allclose(loss_p, loss_r, rtol=2e-4, atol=2e-4)


def test_trainer_rebuilds_on_fused_flag_flip():
    """FLAGS_fused_train is a TRACE-time dispatch input: flipping it
    mid-run must rebuild the step program (not replay the old
    routing), exactly like the nan-check flag."""
    from paddle_tpu.core.flags import GLOBAL_FLAGS
    tr = _trainer(CFG, observability=True)   # fused_train=None -> flag
    state = tr.init_state(llama.init_params(CFG, jax.random.key(0),
                                            dtype=jnp.float32))
    rng = np.random.RandomState(14)
    toks = jnp.asarray(rng.randint(0, 97, (2, 8)), jnp.int32)
    lab = jnp.asarray(np.roll(np.asarray(toks), -1, -1))
    old = GLOBAL_FLAGS.get("fused_train")
    try:
        GLOBAL_FLAGS.set("fused_train", True)
        state, m0 = tr.step(state, toks, lab)
        assert tr.metrics()["compiles"] == 1
        GLOBAL_FLAGS.set("fused_train", False)
        state, m1 = tr.step(state, toks, lab)
        assert tr.metrics()["compiles"] == 2
        # on CPU both routes are the same composition: same math
        assert np.isfinite(float(m1["loss"]))
    finally:
        GLOBAL_FLAGS.set("fused_train", old)
