"""hapi callback machinery (reference: python/paddle/hapi/callbacks.py)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import Model
from paddle_tpu.hapi.callbacks import (Callback, EarlyStopping, LRScheduler,
                                       ModelCheckpoint, ProgBarLogger,
                                       ReduceLROnPlateau, VisualDL,
                                       config_callbacks)


def _small_model(lr=0.05):
    net = nn.Linear(4, 2)
    model = Model(net)
    model.prepare(
        optimizer=paddle.optimizer.SGD(lr, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss())
    return model


def _dataset(n=16):
    from paddle_tpu.io import Dataset

    class DS(Dataset):
        def __len__(self):
            return n

        def __getitem__(self, i):
            r = np.random.RandomState(i)
            x = r.randn(4).astype(np.float32)
            return x, np.int64(i % 2)

    return DS()


class TestLifecycle:
    def test_hooks_fire_in_order(self):
        events = []

        class Spy(Callback):
            def on_train_begin(self, logs=None):
                events.append("train_begin")

            def on_epoch_begin(self, epoch, logs=None):
                events.append(f"epoch_begin{epoch}")

            def on_train_batch_end(self, step, logs=None):
                events.append("batch")
                assert "loss" in logs

            def on_epoch_end(self, epoch, logs=None):
                events.append(f"epoch_end{epoch}")

            def on_eval_begin(self, logs=None):
                events.append("eval_begin")

            def on_eval_end(self, logs=None):
                events.append("eval_end")
                assert "loss" in logs

            def on_train_end(self, logs=None):
                events.append("train_end")

        m = _small_model()
        m.fit(_dataset(8), eval_data=_dataset(8), batch_size=4, epochs=2,
              verbose=0, callbacks=[Spy()], shuffle=False)
        assert events[0] == "train_begin" and events[-1] == "train_end"
        assert events.count("epoch_begin0") == 1
        assert events.count("batch") == 4     # 2 epochs x 2 steps
        assert events.count("eval_begin") == 2

    def test_progbar_prints(self, capsys):
        m = _small_model()
        m.fit(_dataset(8), batch_size=4, epochs=1, verbose=2, log_freq=1,
              callbacks=[ProgBarLogger(log_freq=1, verbose=2)],
              shuffle=False)
        out = capsys.readouterr().out
        assert "Epoch 1/1" in out and "loss" in out


class TestModelCheckpoint:
    def test_saves_epochs_and_final(self, tmp_path):
        m = _small_model()
        m.fit(_dataset(8), batch_size=4, epochs=2, verbose=0,
              save_dir=str(tmp_path), shuffle=False)
        assert (tmp_path / "0.pdparams").exists()
        assert (tmp_path / "1.pdparams").exists()
        assert (tmp_path / "final.pdparams").exists()


class TestEarlyStopping:
    def test_stops_on_plateau(self, tmp_path):
        m = _small_model(lr=0.0)  # nothing improves with lr=0
        es = EarlyStopping(monitor="loss", patience=1, verbose=0,
                           min_delta=0.0)
        epochs_run = []

        class Spy(Callback):
            def on_epoch_begin(self, epoch, logs=None):
                epochs_run.append(epoch)

        m.fit(_dataset(8), eval_data=_dataset(8), batch_size=4, epochs=10,
              verbose=0, callbacks=[es, Spy()], save_dir=str(tmp_path),
              shuffle=False)
        # first eval sets best; evals 2 and 3 don't improve -> stop
        assert len(epochs_run) <= 4
        assert m.stop_training
        assert (tmp_path / "best_model.pdparams").exists()

    def test_improvement_resets_patience(self):
        m = _small_model(lr=0.2)  # actually trains: loss improves
        es = EarlyStopping(monitor="loss", patience=2, verbose=0)
        m.fit(_dataset(16), eval_data=_dataset(16), batch_size=4, epochs=3,
              verbose=0, callbacks=[es], shuffle=False)
        assert np.isfinite(es.best_value)


class TestReduceLROnPlateau:
    def test_lr_halves_on_stall(self):
        m = _small_model(lr=0.08)
        cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                               verbose=0, min_delta=10.0)  # huge delta
        m.fit(_dataset(8), eval_data=_dataset(8), batch_size=4, epochs=3,
              verbose=0, callbacks=[cb], shuffle=False)
        # min_delta=10 means "never improved": epochs 2..3 each stall
        assert m._optimizer.get_lr() == pytest.approx(0.08 * 0.5 * 0.5)

    def test_scheduler_lr_skipped_gracefully(self):
        """Review regression: a scheduler-driven LR must not crash fit;
        the callback warns and skips (reference behavior)."""
        from paddle_tpu.optimizer.lr import StepDecay
        net = nn.Linear(4, 2)
        model = Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(
                StepDecay(learning_rate=0.1, step_size=100),
                parameters=net.parameters()),
            loss=nn.CrossEntropyLoss())
        cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=0,
                               verbose=0, min_delta=10.0)
        model.fit(_dataset(8), eval_data=_dataset(8), batch_size=4,
                  epochs=2, verbose=0, callbacks=[cb], shuffle=False)
        assert model._optimizer.get_lr() == pytest.approx(0.1)

    def test_missing_monitor_is_noop(self):
        m = _small_model(lr=0.05)
        cb = ReduceLROnPlateau(monitor="no_such_metric", factor=0.5,
                               patience=0, verbose=0)
        m.fit(_dataset(8), eval_data=_dataset(8), batch_size=4, epochs=2,
              verbose=0, callbacks=[cb], shuffle=False)
        assert m._optimizer.get_lr() == pytest.approx(0.05)


class TestLRSchedulerCallback:
    def test_steps_scheduler_per_batch(self):
        from paddle_tpu.optimizer.lr import StepDecay
        net = nn.Linear(4, 2)
        sched = StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
        model = Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(sched,
                                           parameters=net.parameters()),
            loss=nn.CrossEntropyLoss())
        model.fit(_dataset(8), batch_size=4, epochs=1, verbose=0,
                  shuffle=False)   # default LRScheduler callback by_step
        # 2 batches -> one decay boundary crossed
        assert model._optimizer.get_lr() == pytest.approx(0.05)


class TestVisualDL:
    def test_writes_scalars(self, tmp_path):
        m = _small_model()
        m.fit(_dataset(8), eval_data=_dataset(8), batch_size=4, epochs=1,
              verbose=0, callbacks=[VisualDL(log_dir=str(tmp_path))],
              shuffle=False)
        path = tmp_path / "scalars.jsonl"
        assert path.exists()
        recs = [json.loads(line) for line in path.read_text().splitlines()]
        assert any("train/loss" in r for r in recs)
        assert any("eval/loss" in r for r in recs)


class TestConfig:
    def test_defaults_installed(self):
        cbks = config_callbacks(None, model=None, verbose=1,
                                save_dir="/tmp/x")
        kinds = [type(c) for c in cbks]
        assert ProgBarLogger in kinds
        assert LRScheduler in kinds
        assert ModelCheckpoint in kinds

    def test_user_progbar_not_duplicated(self):
        user = ProgBarLogger(5)
        cbks = config_callbacks([user], model=None, verbose=1)
        assert sum(isinstance(c, ProgBarLogger) for c in cbks.callbacks) == 1


class TestModelSpecs:
    def test_inference_export_and_predictor_roundtrip(self, tmp_path):
        """Model(inputs=specs).save(training=False) -> loadable by the
        inference Predictor (reference Model.save -> jit.save)."""
        from paddle_tpu.static import InputSpec
        from paddle_tpu.inference import Config, create_predictor
        net = nn.Linear(4, 2)
        # fixed batch: the serialized executable is shape-specialized
        m = Model(net, inputs=[InputSpec([2, 4], "float32")])
        path = str(tmp_path / "infer_model")
        m.save(path, training=False)
        cfg = Config(path)
        pred = create_predictor(cfg)
        x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        out = pred.run([paddle.to_tensor(x)])[0].numpy()
        ref = np.asarray(net(paddle.to_tensor(x)).numpy())
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5,
                                   atol=1e-6)

    def test_save_without_specs_raises(self, tmp_path):
        m = _small_model()
        with pytest.raises(ValueError, match="InputSpec"):
            m.save(str(tmp_path / "x"), training=False)

    def test_summary_uses_specs_for_output_shapes(self):
        from paddle_tpu.static import InputSpec
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        m = Model(net, inputs=[InputSpec([None, 4], "float32")])
        s = m.summary()
        assert s["total_params"] == (4 * 8 + 8) + (8 * 2 + 2)
        # specs drove a forward pass: per-layer output shapes recorded
        assert s["output_shapes"]["0"] == [1, 8]
        assert s["output_shapes"]["2"] == [1, 2]

    def test_numpy_input_spec_and_bad_type(self):
        m = Model(nn.Linear(4, 2),
                  inputs=np.zeros((2, 4), np.float32))
        assert m._inputs[0].shape == [2, 4]
        with pytest.raises(TypeError):
            Model(nn.Linear(4, 2), inputs=[object()])

    def test_shape_specs_accepted(self):
        m = Model(nn.Linear(4, 2), inputs=[[None, 4]])
        assert m._inputs[0].shape == [None, 4]
