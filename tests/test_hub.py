"""paddle.hub local source (reference: python/paddle/hapi/hub.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture
def hub_repo(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        'dependencies = ["numpy"]\n'
        "import numpy as _np\n\n\n"
        "def tiny_mlp(width=4):\n"
        '    """A tiny MLP entrypoint."""\n'
        "    import paddle_tpu.nn as nn\n"
        "    return nn.Linear(width, width)\n\n\n"
        "def _private_helper():\n"
        "    pass\n")
    return str(tmp_path)


def test_list_entrypoints(hub_repo):
    names = paddle.hub.list(hub_repo, source="local")
    assert "tiny_mlp" in names
    assert not any(n.startswith("_") for n in names)


def test_list_includes_imported_callables(tmp_path):
    """Reference behavior: `from x import fn` entrypoints are listed."""
    (tmp_path / "models.py").write_text(
        "def imported_entry():\n    return 42\n")
    (tmp_path / "hubconf.py").write_text(
        "from models import imported_entry\n")
    names = paddle.hub.list(str(tmp_path), source="local")
    assert "imported_entry" in names
    assert paddle.hub.load(str(tmp_path), "imported_entry",
                           source="local") == 42


def test_help_and_load(hub_repo):
    doc = paddle.hub.help(hub_repo, "tiny_mlp", source="local")
    assert "tiny MLP" in doc
    net = paddle.hub.load(hub_repo, "tiny_mlp", source="local", width=6)
    x = paddle.to_tensor(np.ones((2, 6), np.float32))
    assert list(net(x).shape) == [2, 6]


def test_missing_dependency_raises(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        'dependencies = ["not_a_real_pkg_xyz"]\n'
        "def f():\n    return 1\n")
    with pytest.raises(RuntimeError, match="Missing dependencies"):
        paddle.hub.list(str(tmp_path), source="local")


def test_remote_sources_gated(hub_repo):
    with pytest.raises(RuntimeError, match="egress"):
        paddle.hub.load("PaddlePaddle/PaddleClas", "resnet50")
    with pytest.raises(ValueError, match="Unknown source"):
        paddle.hub.list(hub_repo, source="svn")


def test_bad_entry_raises(hub_repo):
    with pytest.raises(RuntimeError, match="Cannot find callable"):
        paddle.hub.load(hub_repo, "nope", source="local")
