"""Inference engine tests: KV-cache decode parity, generation, paged
attention, Predictor (reference test model: test/inference/ predictor
golden tests + fused_multi_transformer unit tests)."""
import json
import math
import os

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models import llama
from paddle_tpu.inference import (GenerationConfig, generate,
                                  cached_forward, init_cache)
from paddle_tpu.ops.paged_attention import (paged_attention_decode,
                                            write_to_pool, BlockManager)

CFG = llama.LlamaConfig(vocab_size=128, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        max_position_embeddings=64, dtype=jnp.float32,
                        remat=False)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


@pytest.mark.slow
def test_cached_forward_matches_uncached(params):
    B, S = 2, 10
    toks = jax.random.randint(jax.random.key(1), (B, S), 0, CFG.vocab_size)
    ref_logits = llama.forward(params, toks, CFG)
    kc, vc = init_cache(CFG, B, S, dtype=jnp.float32)
    logits, kc, vc = cached_forward(params, toks, CFG, kc, vc, 0)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_incremental_decode_matches_full_forward(params):
    """Prefill S tokens then decode one-by-one must equal the full
    forward over the whole sequence (the KV cache correctness check)."""
    B, S, N = 1, 6, 4
    key = jax.random.key(2)
    toks = jax.random.randint(key, (B, S + N), 0, CFG.vocab_size)
    full_logits = llama.forward(params, toks, CFG)

    T = S + N
    kc, vc = init_cache(CFG, B, T, dtype=jnp.float32)
    logits, kc, vc = cached_forward(params, toks[:, :S], CFG, kc, vc, 0)
    np.testing.assert_allclose(np.asarray(logits[:, -1]),
                               np.asarray(full_logits[:, S - 1]),
                               rtol=2e-4, atol=2e-4)
    for i in range(N - 1):
        step_logits, kc, vc = cached_forward(
            params, toks[:, S + i:S + i + 1], CFG, kc, vc, S + i)
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full_logits[:, S + i]),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_generate_greedy_shape_and_determinism(params):
    B, S = 2, 5
    toks = jax.random.randint(jax.random.key(3), (B, S), 0, CFG.vocab_size)
    gen = GenerationConfig(max_new_tokens=8, greedy=True)
    out1 = generate(params, toks, CFG, gen)
    out2 = generate(params, toks, CFG, gen)
    assert out1.shape == (B, S + 8)
    assert np.array_equal(np.asarray(out1), np.asarray(out2))
    assert np.array_equal(np.asarray(out1[:, :S]), np.asarray(toks))


@pytest.mark.slow
def test_generate_greedy_matches_stepwise_argmax(params):
    """Greedy generate must equal manual argmax rollout through the
    uncached forward (ground truth)."""
    B, S, N = 1, 4, 5
    toks = jax.random.randint(jax.random.key(4), (B, S), 0, CFG.vocab_size)
    out = generate(params, toks, CFG,
                   GenerationConfig(max_new_tokens=N, greedy=True))
    cur = toks
    for _ in range(N):
        logits = llama.forward(params, cur, CFG)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    assert np.array_equal(np.asarray(out), np.asarray(cur))


@pytest.mark.slow
def test_generate_eos_padding(params):
    B, S = 1, 4
    toks = jax.random.randint(jax.random.key(5), (B, S), 0, CFG.vocab_size)
    gen = GenerationConfig(max_new_tokens=6, greedy=True)
    out = generate(params, toks, CFG, gen)
    # force eos at whatever greedy produces first → all later = eos
    first = int(np.asarray(out)[0, S])
    gen2 = GenerationConfig(max_new_tokens=6, greedy=True,
                            eos_token_id=first)
    out2 = np.asarray(generate(params, toks, CFG, gen2))
    assert (out2[0, S:] == first).all() or (
        out2[0, S] == first and (out2[0, S + 1:] == first).all())


@pytest.mark.slow
def test_sampling_topk_topp_valid(params):
    B, S = 2, 4
    toks = jax.random.randint(jax.random.key(6), (B, S), 0, CFG.vocab_size)
    gen = GenerationConfig(max_new_tokens=5, temperature=0.8, top_k=10,
                           top_p=0.9)
    out = np.asarray(generate(params, toks, CFG, gen, seed=7))
    assert out.shape == (B, S + 5)
    assert ((out >= 0) & (out < CFG.vocab_size)).all()


# -- paged attention --------------------------------------------------------
def _dense_decode_ref(q, k, v, seq_lens):
    """q [B,H,hd], k/v [B,T,H,hd] → masked attention ground truth."""
    B, H, hd = q.shape
    scores = jnp.einsum("bhd,bthd->bht", q, k) / math.sqrt(hd)
    mask = jnp.arange(k.shape[1])[None, None, :] < seq_lens[:, None, None]
    scores = jnp.where(mask, scores, -jnp.inf)
    return jnp.einsum("bht,bthd->bhd", jax.nn.softmax(scores, -1), v)


@pytest.mark.slow
def test_paged_attention_matches_dense():
    B, H, KV, hd, BS, MB = 2, 4, 2, 16, 4, 3
    N = 8   # physical blocks in pool
    T = MB * BS
    key = jax.random.key(0)
    ks = jax.random.split(key, 4)
    seq_lens = jnp.array([7, 11], jnp.int32)
    k_dense = jax.random.normal(ks[0], (B, T, KV, hd))
    v_dense = jax.random.normal(ks[1], (B, T, KV, hd))
    q = jax.random.normal(ks[2], (B, H, hd))

    # scatter dense kv into a shuffled block pool
    block_tables = jnp.array([[5, 2, 7], [1, 4, 0]], jnp.int32)
    k_pool = jnp.zeros((N, BS, KV, hd))
    v_pool = jnp.zeros((N, BS, KV, hd))
    for b in range(B):
        for m in range(MB):
            phys = int(block_tables[b, m])
            k_pool = k_pool.at[phys].set(k_dense[b, m * BS:(m + 1) * BS])
            v_pool = v_pool.at[phys].set(v_dense[b, m * BS:(m + 1) * BS])

    out = paged_attention_decode(q, k_pool, v_pool, block_tables, seq_lens)
    rep = H // KV
    k_rep = jnp.repeat(k_dense, rep, axis=2)
    v_rep = jnp.repeat(v_dense, rep, axis=2)
    ref = _dense_decode_ref(q, k_rep, v_rep, seq_lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_write_to_pool_then_attend():
    B, KV, hd, BS, MB, N = 1, 2, 8, 4, 2, 4
    block_tables = jnp.array([[2, 0]], jnp.int32)
    k_pool = jnp.zeros((N, BS, KV, hd))
    v_pool = jnp.zeros((N, BS, KV, hd))
    keys = jax.random.split(jax.random.key(1), 6)
    toks_k = [jax.random.normal(keys[i], (B, KV, hd)) for i in range(6)]
    toks_v = [jax.random.normal(keys[i], (B, KV, hd)) * 0.5
              for i in range(6)]
    for i in range(6):
        k_pool, v_pool = write_to_pool(
            k_pool, v_pool, block_tables,
            jnp.array([i], jnp.int32), toks_k[i], toks_v[i])
    # block 2 holds tokens 0-3, block 0 holds tokens 4-5
    got = jnp.take(k_pool, block_tables[0], axis=0).reshape(MB * BS, KV, hd)
    for i in range(6):
        np.testing.assert_allclose(np.asarray(got[i]),
                                   np.asarray(toks_k[i][0]), rtol=1e-6)


def test_block_manager():
    bm = BlockManager(num_blocks=8, block_size=4, max_blocks_per_seq=4)
    t1 = bm.allocate(1, 6)      # 2 blocks
    assert len(t1) == 2
    t2 = bm.allocate(2, 10)     # 3 blocks
    assert len(t2) == 3 and not set(t1) & set(t2)
    arr = bm.table_array([1, 2])
    assert arr.shape == (2, 4)
    assert list(arr[0, :2]) == t1
    bm.release(1)
    t3 = bm.allocate(3, 16)     # 4 blocks — reuses released ones
    assert len(t3) == 4
    with pytest.raises(RuntimeError):
        bm.allocate(4, 100)


# -- predictor --------------------------------------------------------------
def test_predictor_roundtrip(tmp_path):
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec
    from paddle_tpu.inference import Config, create_predictor

    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    net.eval()
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 8)
                         .astype(np.float32))
    ref = net(x).numpy()
    path = str(tmp_path / "model")
    paddle.jit.save(net, path, input_spec=[InputSpec([2, 8], "float32")])

    cfg = Config(path)
    pred = create_predictor(cfg)
    names = pred.get_input_names()
    h = pred.get_input_handle(names[0])
    h.copy_from_cpu(np.asarray(x.numpy()))
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    # positional style
    outs = pred.run([x])
    np.testing.assert_allclose(outs[0].numpy(), ref, rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_predictor_aot_cache_restart_skips_compile(tmp_path):
    """VERDICT round-2 #9: Predictor keeps a serialized-executable cache
    (AnalysisConfig::SetOptimCacheDir analog) so a process RESTART skips
    XLA compilation. Two real processes: the first compiles and writes
    the cache, the second must load it (last_run_from_cache=True) and
    produce identical outputs."""
    import subprocess
    import sys

    script = r"""
import sys, json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, %r)
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static import InputSpec
from paddle_tpu.inference import Config, create_predictor

model, cache, out_file, phase = sys.argv[1:5]
if phase == "save":
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    net.eval()
    paddle.jit.save(net, model, input_spec=[InputSpec([2, 8], "float32")])
cfg = Config(model)
cfg.set_optim_cache_dir(cache)
pred = create_predictor(cfg)
x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
out = pred.run([x])[0].numpy()
json.dump({"from_cache": bool(pred.last_run_from_cache),
           "out": np.asarray(out).tolist()}, open(out_file, "w"))
""" % REPO

    model = str(tmp_path / "model")
    cache = str(tmp_path / "xcache")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)   # single-device CPU, the deploy shape
    env["JAX_PLATFORMS"] = "cpu"

    def run_phase(phase, out_name):
        out_file = str(tmp_path / out_name)
        p = subprocess.run(
            [sys.executable, "-c", script, model, cache, out_file, phase],
            env=env, capture_output=True, text=True, timeout=240)
        assert p.returncode == 0, (p.stdout[-1000:], p.stderr[-1000:])
        with open(out_file) as f:
            return json.load(f)

    r1 = run_phase("save", "r1.json")
    assert r1["from_cache"] is False          # first process compiled
    assert os.path.isdir(cache) and os.listdir(cache)
    r2 = run_phase("load", "r2.json")
    assert r2["from_cache"] is True, \
        "restarted process recompiled instead of loading the executable"
    np.testing.assert_allclose(r1["out"], r2["out"], rtol=1e-6)


def test_paged_pallas_kernel_matches_fallback():
    """Pallas paged decode (interpret mode) vs the XLA gather+einsum."""
    from paddle_tpu.ops.pallas import _util
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_attention_decode_pallas)
    rng = np.random.RandomState(0)
    B, H, KV, hd, N, BS, MB = 4, 8, 2, 128, 36, 16, 8
    q = jnp.asarray(rng.randn(B, H, hd), jnp.float32)
    kp = jnp.asarray(rng.randn(N, BS, KV, hd), jnp.float32)
    vp = jnp.asarray(rng.randn(N, BS, KV, hd), jnp.float32)
    bt = jnp.asarray(rng.permutation(N)[:B * MB].reshape(B, MB), jnp.int32)
    sl = jnp.asarray([1, 37, 0, 128], jnp.int32)
    from paddle_tpu.ops.paged_attention import paged_attention_decode_xla
    ref = paged_attention_decode_xla(q, kp, vp, bt, sl)
    prev = _util._FORCE_INTERPRET
    _util.set_force_interpret(True)
    try:
        out = paged_attention_decode_pallas(q, kp, vp, bt, sl)
    finally:
        _util.set_force_interpret(prev)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    assert float(jnp.abs(out[2]).max()) == 0.0  # seq_len 0 slot


@pytest.mark.slow
def test_generate_paged_matches_dense_greedy():
    """vLLM-style paged serving loop == dense-cache generation."""
    from paddle_tpu.inference.generation import generate_paged
    cfg = llama.LlamaConfig(vocab_size=97, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128,
                      dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 97, (2, 9)),
                      jnp.int32)
    g = GenerationConfig(max_new_tokens=6, greedy=True)
    dense = generate(params, ids, cfg, g)
    paged = generate_paged(params, ids, cfg, g, block_size=4)
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(paged))


@pytest.mark.slow
def test_shared_cache_dir_two_models_no_eviction(tmp_path):
    """Advisor fix: two Predictors sharing one set_optim_cache_dir get
    per-model-path subdirectories and must not evict each other."""
    import os
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec
    from paddle_tpu.inference import Config, create_predictor

    cache = str(tmp_path / "shared_cache")
    paths = []
    for i, width in enumerate((8, 16)):
        net = nn.Sequential(nn.Linear(4, width), nn.ReLU(),
                            nn.Linear(width, 2))
        net.eval()
        p = str(tmp_path / f"model{i}")
        paddle.jit.save(net, p, input_spec=[InputSpec([1, 4], "float32")])
        paths.append(p)

    x = np.random.RandomState(0).randn(1, 4).astype(np.float32)
    preds = []
    for p in paths:
        cfg = Config(p)
        cfg.set_optim_cache_dir(cache)
        pr = create_predictor(cfg)
        pr.run([paddle.to_tensor(x)])
        preds.append(pr)

    def count_pdexec():
        n = 0
        for root, _, files in os.walk(cache):
            n += sum(f.endswith(".pdexec") for f in files)
        return n

    n_after_both = count_pdexec()
    assert n_after_both >= 2   # both models' executables coexist
    # pruning model 0's stale entries must not touch model 1's subdir
    preds[0]._prune_stale()
    preds[1]._prune_stale()
    assert count_pdexec() == n_after_both


def test_generate_paged_chunk_size_invariant(monkeypatch):
    """Chunked decode (PADDLE_TPU_DECODE_CHUNK) must not change results:
    a chunk boundary is only a host dispatch boundary."""
    import jax
    from paddle_tpu.inference.generation import (GenerationConfig,
                                                 generate_paged)
    from paddle_tpu.models.llama import LlamaConfig, init_params
    cfg = LlamaConfig(vocab_size=97, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64)
    params = init_params(cfg, jax.random.PRNGKey(3))
    ids = np.random.RandomState(0).randint(0, 97, (2, 7)).astype(np.int32)
    g = GenerationConfig(max_new_tokens=9, greedy=True)
    outs = []
    for chunk in ("2", "64"):
        monkeypatch.setenv("PADDLE_TPU_DECODE_CHUNK", chunk)
        outs.append(np.asarray(generate_paged(params, ids, cfg, g,
                                              block_size=4)))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_serving_engine_matches_generate_paged_greedy():
    """The continuous-batching engine and the static paged loop must
    agree token-for-token on a greedy 2-request batch (same pools, same
    decode math — only the scheduler differs)."""
    from paddle_tpu.inference.generation import generate_paged
    from paddle_tpu.inference.serving import ServingEngine
    cfg = llama.LlamaConfig(vocab_size=97, hidden_size=64,
                            intermediate_size=128, num_hidden_layers=2,
                            num_attention_heads=4, num_key_value_heads=2,
                            max_position_embeddings=128,
                            dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 97, (2, 9)),
                      jnp.int32)
    g = GenerationConfig(max_new_tokens=6, greedy=True)
    static = np.asarray(generate_paged(params, ids, cfg, g,
                                       block_size=4))
    eng = ServingEngine(params, cfg, capacity=2, block_size=4,
                        prefill_buckets=(16,), max_seq_len=32)
    reqs = [eng.submit(np.asarray(ids[b]), g) for b in range(2)]
    eng.drain()
    for b, r in enumerate(reqs):
        np.testing.assert_array_equal(r.output_ids, static[b])


def test_generate_paged_runner_cached_across_calls():
    """The jitted chunk runner must be reused across serving requests
    (a fresh jit per call re-traces the whole decode scan)."""
    import jax
    from paddle_tpu.inference import generation as G
    from paddle_tpu.models.llama import LlamaConfig, init_params
    cfg = LlamaConfig(vocab_size=61, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=2,
                      num_key_value_heads=2, max_position_embeddings=32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = np.zeros((1, 4), np.int32)
    g = G.GenerationConfig(max_new_tokens=3, greedy=True)
    G._PAGED_CACHE.clear()
    G.generate_paged(params, ids, cfg, g, block_size=4)
    assert len(G._PAGED_CACHE) == 1
    runner = next(iter(G._PAGED_CACHE.values()))
    G.generate_paged(params, ids, cfg, g, block_size=4)
    assert len(G._PAGED_CACHE) == 1
    assert next(iter(G._PAGED_CACHE.values())) is runner
