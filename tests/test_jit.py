"""to_static / compiled-graph tests (reference analog: test/dygraph_to_static/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestToStatic:
    def test_function_parity(self):
        @paddle.jit.to_static
        def f(x, y):
            return paddle.matmul(x, y) + 1.0

        a = paddle.randn([3, 4])
        b = paddle.randn([4, 5])
        want = a.numpy() @ b.numpy() + 1.0
        np.testing.assert_allclose(f(a, b).numpy(), want, rtol=1e-5,
                                   atol=1e-5)
        # second call hits cache
        np.testing.assert_allclose(f(a, b).numpy(), want, rtol=1e-5,
                                   atol=1e-5)

    def test_layer_parity_and_grad(self):
        net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 2))
        x = paddle.randn([8, 4])
        eager_out = net(x).numpy()
        snet = paddle.jit.to_static(net)
        out = snet(x)
        np.testing.assert_allclose(out.numpy(), eager_out, rtol=1e-5,
                                   atol=1e-5)
        # grads flow through the compiled region
        loss = out.sum()
        loss.backward()
        for p in net.parameters():
            assert p.grad is not None

    def test_compiled_training_matches_eager(self):
        paddle.seed(1)
        net_e = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
        paddle.seed(1)
        net_s = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
        np.testing.assert_allclose(net_e[0].weight.numpy(),
                                   net_s[0].weight.numpy())
        opt_e = paddle.optimizer.SGD(0.1, parameters=net_e.parameters(),
                                     multi_precision=False)
        opt_s = paddle.optimizer.SGD(0.1, parameters=net_s.parameters(),
                                     multi_precision=False)
        compiled = paddle.jit.to_static(net_s)
        x = paddle.randn([16, 4])
        y = paddle.randn([16, 1])
        for _ in range(3):
            le = F.mse_loss(net_e(x), y)
            le.backward()
            opt_e.step()
            opt_e.clear_grad()
            ls = F.mse_loss(compiled(x), y)
            ls.backward()
            opt_s.step()
            opt_s.clear_grad()
        np.testing.assert_allclose(net_e[0].weight.numpy(),
                                   net_s[0].weight.numpy(), rtol=1e-4,
                                   atol=1e-5)

    @pytest.mark.slow
    def test_buffer_updates_propagate(self):
        net = nn.Sequential(nn.Conv2D(1, 2, 3), nn.BatchNorm2D(2))
        compiled = paddle.jit.to_static(net)
        bn = net[1]
        m0 = bn._mean.numpy().copy()
        compiled(paddle.randn([4, 1, 6, 6]) + 3.0)
        assert not np.allclose(m0, bn._mean.numpy())

    def test_shape_recompile(self):
        calls = []

        @paddle.jit.to_static
        def f(x):
            calls.append(1)
            return x * 2

        f(paddle.randn([2, 3]))
        n1 = len(calls)
        f(paddle.randn([2, 3]))  # cached: no retrace
        assert len(calls) == n1
        f(paddle.randn([4, 3]))  # new shape: retrace
        assert len(calls) > n1

    def test_dropout_varies_under_jit(self):
        d = nn.Dropout(0.5)
        d.train()
        f = paddle.jit.to_static(lambda x: d(x))
        x = paddle.ones([64, 64])
        a = f(x).numpy()
        b = f(x).numpy()
        assert not np.allclose(a, b)

    def test_static_export_stablehlo(self):
        import jax.numpy as jnp
        txt = paddle.static.export_stablehlo(
            lambda x: jnp.tanh(x) * 2, (paddle.randn([2, 2]),))
        assert "stablehlo" in txt or "mhlo" in txt or "tanh" in txt

    def test_jit_save_load(self, tmp_path):
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        net.eval()
        path = str(tmp_path / "m")
        from paddle_tpu.static import InputSpec
        paddle.jit.save(net, path, input_spec=[InputSpec([1, 4])])
        loaded = paddle.jit.load(path)
        x = paddle.randn([1, 4])
        np.testing.assert_allclose(loaded(x).numpy(), net(x).numpy(),
                                   rtol=1e-5, atol=1e-6)


class TestNanWatchCompiled:
    """FLAGS_check_nan_inf must catch non-finite values inside compiled
    train steps (reference: framework/new_executor/nan_inf_utils.cc)."""

    def test_train_step_catches_injected_nan(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        from paddle_tpu.core.flags import GLOBAL_FLAGS

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 4))
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        ts = paddle.jit.train_step(net, lambda o, y: F.mse_loss(o, y), opt)
        x = np.zeros((4, 4), np.float32)
        x[0, 0] = np.inf  # forward -> inf -> loss nan/inf
        y = np.zeros((4, 4), np.float32)
        GLOBAL_FLAGS.set("check_nan_inf", True)
        try:
            with pytest.raises(FloatingPointError, match="check_nan_inf"):
                ts(paddle.to_tensor(x), paddle.to_tensor(y))
        finally:
            GLOBAL_FLAGS.set("check_nan_inf", False)
        # and clean inputs pass with the flag on
        GLOBAL_FLAGS.set("check_nan_inf", True)
        try:
            loss = ts(paddle.to_tensor(y), paddle.to_tensor(y))
            assert np.isfinite(float(loss))
        finally:
            GLOBAL_FLAGS.set("check_nan_inf", False)

    def test_memory_stats_surface(self):
        import paddle_tpu as paddle
        s = paddle.device.memory_stats()
        assert isinstance(s, dict)
        # CPU PjRt may expose no stats; the API must still answer ints
        assert isinstance(paddle.device.max_memory_allocated(), int)
        assert isinstance(paddle.device.memory_allocated(), int)


def test_to_static_graph_break_fallback():
    """Tensor-dependent Python control flow falls back to eager (the
    reference SOT's graph-break semantics) instead of erroring."""
    import warnings
    import paddle_tpu as paddle

    @paddle.jit.to_static
    def f(x):
        if float(x.sum()) > 0:    # concretizes a tracer -> graph break
            return x * 2
        return x - 1

    x = paddle.to_tensor(np.ones((3,), np.float32))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = f(x)
        assert any("graph break" in str(e.message) for e in w)
    np.testing.assert_allclose(np.asarray(out._value), 2 * np.ones(3))
    # second call with same signature but flipped branch: still correct
    y = paddle.to_tensor(-np.ones((3,), np.float32))
    out2 = f(y)
    np.testing.assert_allclose(np.asarray(out2._value), -2 * np.ones(3))


class TestSOTSegments:
    """SOT-parity segmented execution around graph breaks (VERDICT
    round-2 #8; reference python/paddle/jit/sot/translate.py:37)."""

    def _make(self, n_layers=10):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        paddle.seed(3)
        layers = []
        for _ in range(n_layers):
            layers += [nn.Linear(8, 8), nn.Tanh()]
        net = nn.Sequential(*layers)

        @paddle.jit.to_static
        def f(x):
            h = net(x)
            if float(h.mean()) > 0:    # the one data-dependent branch
                h = h * 2.0
            else:
                h = h - 1.0
            return net(h)

        def ref(x):
            h = net(x)
            if float(np.asarray(h.numpy()).mean()) > 0:
                h = h * 2.0
            else:
                h = h - 1.0
            return np.asarray(net(h).numpy())

        return f, ref

    def _seg_entry(self, f, x):
        entry = f._cache[f._key((x,), {})]
        assert entry[0] == "sot", entry
        return entry[1]

    def test_segments_stay_compiled_90pct(self):
        import warnings
        import paddle_tpu as paddle
        f, ref = self._make()
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                             .astype(np.float32))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            f(x)                       # break -> record
            out = f(x)                 # replay compiled segments
        seg = self._seg_entry(f, x)
        assert seg.last_was_replay
        total, compiled = seg.stats
        assert total >= 20             # a real model, not a toy
        assert compiled / total >= 0.9, (compiled, total)
        np.testing.assert_allclose(np.asarray(out.numpy()), ref(x),
                                   rtol=2e-5, atol=2e-6)

    def test_guard_flip_rerecords_then_replays(self):
        import warnings
        import paddle_tpu as paddle
        f, ref = self._make(4)
        # big positive vs big negative input flips the branch
        xp = paddle.to_tensor(np.full((4, 8), 2.0, np.float32))
        xn = paddle.to_tensor(np.full((4, 8), -2.0, np.float32))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            f(xp)                      # record path A
            assert self._seg_entry(f, xp).last_was_replay is False
            f(xp)
            assert self._seg_entry(f, xp).last_was_replay is True
            out_n = f(xn)              # guard mismatch -> re-record
            assert self._seg_entry(f, xn).last_was_replay is False
            np.testing.assert_allclose(np.asarray(out_n.numpy()), ref(xn),
                                       rtol=2e-5, atol=2e-6)
            out_n2 = f(xn)             # new path replays
            assert self._seg_entry(f, xn).last_was_replay is True
            np.testing.assert_allclose(np.asarray(out_n2.numpy()), ref(xn),
                                       rtol=2e-5, atol=2e-6)

    def test_backward_through_segments_matches_eager(self):
        import warnings
        import paddle_tpu as paddle
        f, _ = self._make(4)
        xv = np.random.RandomState(1).randn(4, 8).astype(np.float32)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            x0 = paddle.to_tensor(xv)
            f(x0)                      # record
            x1 = paddle.to_tensor(xv)
            x1.stop_gradient = False
            out = f(x1)                # replayed segments on the tape
            out.sum().backward()
        seg = self._seg_entry(f, x1)
        assert seg.last_was_replay
        # eager reference
        x2 = paddle.to_tensor(xv)
        x2.stop_gradient = False
        out2 = f._fn(x2)
        out2.sum().backward()
        np.testing.assert_allclose(np.asarray(x1.grad.numpy()),
                                   np.asarray(x2.grad.numpy()),
                                   rtol=2e-5, atol=1e-6)

    def test_module_level_flag_is_guarded(self):
        """A tensor consumed as a scalar before any op sees it must still
        be guarded: changing it between calls must not replay the stale
        control path."""
        import warnings
        import paddle_tpu as paddle
        flag = paddle.to_tensor(np.float32(1.0))

        @paddle.jit.to_static
        def h(x):
            if x.sum() > -1e30:     # genuine break -> SOT path
                x = x * 1.0
            if float(flag) > 0:     # unknown-to-recorder consumption
                return x * 2.0
            return x - 1.0

        x = paddle.to_tensor(np.ones((3,), np.float32))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            h(x)                                 # record path A
            out_a = h(x)                         # replay path A
            np.testing.assert_allclose(np.asarray(out_a.numpy()),
                                       2 * np.ones(3))
            flag.set_value(paddle.to_tensor(np.float32(-1.0)))
            out_b = h(x)                         # guard must catch this
            np.testing.assert_allclose(np.asarray(out_b.numpy()),
                                       np.zeros(3))

    def test_input_inplace_mutation_falls_back(self):
        """A function mutating its argument in place must not be replayed
        (the mutation would be skipped)."""
        import warnings
        import paddle_tpu as paddle

        @paddle.jit.to_static
        def h(x):
            x[0] = 0.0
            if x.sum() > -1e30:
                return x * 2.0
            return x

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for _ in range(3):
                x = paddle.to_tensor(np.ones((3,), np.float32))
                out = h(x)
                np.testing.assert_allclose(np.asarray(out.numpy()),
                                           [0.0, 2.0, 2.0])
                np.testing.assert_allclose(np.asarray(x.numpy()),
                                           [0.0, 1.0, 1.0])
        entry = h._cache[h._key((x,), {})]
        assert entry[0] == "sot" and entry[1]._never_replay

    def test_external_mutation_falls_back_to_eager(self):
        """A call that mutates captured state (BN running stats in train
        mode) must not be replayed — side effects don't replay."""
        import warnings
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        paddle.seed(0)
        bn = nn.BatchNorm1D(8)
        bn.train()

        @paddle.jit.to_static
        def g(x):
            h = bn(x)
            if float(h.sum()) > -1e30:   # always-true break
                return h * 1.0
            return h

        x = paddle.to_tensor(np.random.RandomState(2).randn(4, 8)
                             .astype(np.float32))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            g(x)
            m1 = np.asarray(bn._mean.numpy()).copy()
            g(x)
            m2 = np.asarray(bn._mean.numpy()).copy()
        entry = g._cache[g._key((x,), {})]
        assert entry[0] == "sot" and entry[1]._never_replay
        # running stats kept updating because both calls ran eagerly
        assert not np.allclose(m1, m2)


# -- static.Program facade (reference: base/framework.py Program,
# base/executor.py Executor) ------------------------------------------------
class TestStaticProgram:
    def test_build_run_refeed(self):
        from paddle_tpu import static

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 8], "float32")
            h = static.nn.fc(x, 16, activation="relu")
            y = static.nn.fc(h, 4)
            loss = (y * y).mean()
        exe = static.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        a1 = rng.randn(5, 8).astype(np.float32)
        out1, l1 = exe.run(main, feed={"x": a1}, fetch_list=[y, loss])
        assert out1.shape == (5, 4) and np.isfinite(l1).all()
        # different batch size hits a fresh jit cache entry
        out2, = exe.run(main, feed={"x": rng.randn(3, 8)
                                    .astype(np.float32)}, fetch_list=[y])
        assert out2.shape == (3, 4)
        # determinism + clone
        out1b, = exe.run(main, feed={"x": a1}, fetch_list=[y])
        np.testing.assert_allclose(out1, out1b)
        out1c, = exe.run(main.clone(), feed={"x": a1}, fetch_list=[y])
        np.testing.assert_allclose(out1, out1c)

    def test_missing_feed_and_bad_fetch_raise(self):
        from paddle_tpu import static

        main = static.Program()
        with static.program_guard(main):
            x = static.data("inp", [None, 2], "float32")
            y = x + 1.0
        exe = static.Executor()
        with pytest.raises(ValueError):
            exe.run(main, feed={}, fetch_list=[y])
        stranger = paddle.to_tensor(np.zeros(2, np.float32))
        with pytest.raises(ValueError):
            exe.run(main, feed={"inp": np.zeros((1, 2), np.float32)},
                    fetch_list=[stranger])

    def test_embedding_and_batch_norm_builders(self):
        from paddle_tpu import static

        main = static.Program()
        with static.program_guard(main):
            ids = static.data("ids", [None, 4], "int64")
            emb = static.nn.embedding(ids, size=(10, 6))
            img = static.data("img", [None, 3, 4, 4], "float32")
            bn = static.nn.batch_norm(img)
        exe = static.Executor()
        e, b = exe.run(main, feed={
            "ids": np.zeros((2, 4), np.int64),
            "img": np.random.randn(2, 3, 4, 4).astype(np.float32)},
            fetch_list=[emb, bn])
        assert e.shape == (2, 4, 6) and b.shape == (2, 3, 4, 4)

    def test_recording_does_not_leak_outside_guard(self):
        from paddle_tpu import static
        from paddle_tpu.core import tensor as _ct

        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            _ = x * 3.0
        n_ops = len(main._ops)
        _ = paddle.to_tensor([1.0]) + 1.0   # outside: not recorded
        assert len(main._ops) == n_ops
        assert _ct._PROGRAM_RECORDER[0] is None


def test_static_program_redraws_dropout_each_run():
    """reference static graphs draw a fresh seed per Executor.run; the
    recorded replay must NOT bake the record-time mask."""
    from paddle_tpu import static
    import paddle_tpu.nn.functional as F

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 64], "float32")
        y = F.dropout(x, p=0.5, training=True)
    exe = static.Executor()
    feed = {"x": np.ones((4, 64), np.float32)}
    a = exe.run(main, feed=feed, fetch_list=[y])[0]
    b = exe.run(main, feed=feed, fetch_list=[y])[0]
    assert not np.allclose(a, b)


def test_sot_const_output_not_aliased_across_replays():
    """Advisor fix: a const output slot must hand out a FRESH Tensor per
    replay; mutating the returned tensor in place must not corrupt
    later replays of the same signature."""
    import paddle_tpu as paddle
    captured = paddle.to_tensor(np.array([10.0, 20.0], np.float32))

    @paddle.jit.to_static
    def f(x):
        if float(x.sum()) > 0:   # graph break -> SOT recording
            pass
        return captured          # const output slot (untouched passthrough)

    x = paddle.to_tensor(np.ones(2, np.float32))
    f(x)          # recording pass (returns the user's own tensor)
    out2 = f(x)   # replayed: must be a fresh wrapper
    out2.set_value(paddle.to_tensor(np.array([-1.0, -1.0], np.float32)))
    out3 = f(x)   # mutation of a replayed output must not leak
    assert out3 is not out2
    np.testing.assert_allclose(out3.numpy(), [10.0, 20.0])
