"""Kernel-geometry auditor: capture layer, rule passes, the tier-1
gate vs the committed KERNEL_AUDIT_BASELINE.json, the CLI contract,
and the registry-wide pallas-vs-fallback differential sweep."""
import glob
import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.kernel_audit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "kernel_audit.py")
COMMITTED_BASELINE = os.path.join(REPO, "KERNEL_AUDIT_BASELINE.json")

# importing the kernel modules registers every op (the differential
# sweep and the coverage assertions iterate the live registry)
from paddle_tpu.ops.pallas import (fused_adamw as fa,           # noqa: E402
                                   fused_decode_block as fdb,
                                   fused_prefill_block as fpb,
                                   fused_train as ft, norms)
from paddle_tpu.ops.pallas._util import (KernelLaunchSpec,      # noqa: E402
                                         KernelOperand,
                                         capture_kernel_launches)
from paddle_tpu.ops.pallas.registry import KERNELS              # noqa: E402


def _run(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, CLI, *args],
                          capture_output=True, text=True, env=env,
                          timeout=600)


# -- the tier-1 gate (in-process: ONE capture+audit of the catalog,
# shared by the gate and coverage assertions) --------------------------

@pytest.fixture(scope="module")
def catalog_reports():
    from paddle_tpu.analysis.kernel_catalog import audit_kernels
    return audit_kernels()


def test_kernel_audit_gate_catalog_clean_vs_committed_baseline(
        catalog_reports):
    """THE gate: every kernel case (tiny + flagship serving/training
    shape classes) plus the registry lint, audited against the
    committed baseline — no new findings. A regression here means a
    kernel's launch geometry (grid coverage, bounds, write
    injectivity, VMEM windows, dispatch-key coverage) changed in a way
    the baseline does not accept."""
    from paddle_tpu.analysis import diff_findings, load_baseline
    baseline = load_baseline(COMMITTED_BASELINE)
    new, _fixed = diff_findings(catalog_reports, baseline)
    assert new == [], "\n".join(
        f"{f.fingerprint}: {f.message}" for f in new)


def test_demo_regression_fails_the_gate_in_process():
    """The injected pre-fix non-divisor block_f kernel must produce
    NEW GRID_FLOOR_DROP findings vs the committed baseline — the gate
    can actually fail on the review-caught bug class."""
    from paddle_tpu.analysis import diff_findings, load_baseline
    from paddle_tpu.analysis.kernel_catalog import (
        build_demo_kernel_regression)
    rep = build_demo_kernel_regression()
    new, _ = diff_findings([rep], load_baseline(COMMITTED_BASELINE))
    assert {f.code for f in new} == {"GRID_FLOOR_DROP"}
    assert len(new) >= 2            # wg AND wu tails are both dropped


# -- coverage: no unaudited pallas_call --------------------------------

def test_every_pallas_call_site_routes_through_the_capture_layer():
    """Static proof that no kernel can dodge the auditor: the ONLY
    ``pl.pallas_call`` call site under ops/pallas/ is the
    audited_pallas_call gateway in _util.py itself."""
    offenders = {}
    root = os.path.join(REPO, "paddle_tpu", "ops", "pallas")
    for path in glob.glob(os.path.join(root, "**", "*.py"),
                          recursive=True):
        with open(path) as fh:
            hits = len(re.findall(r"pl\.pallas_call\s*\(", fh.read()))
        if hits and os.path.relpath(path, root) != "_util.py":
            offenders[os.path.relpath(path, root)] = hits
    assert offenders == {}, (
        f"direct pl.pallas_call site(s) outside audited_pallas_call: "
        f"{offenders} — route them through ops/pallas/_util."
        f"audited_pallas_call so the geometry auditor sees them")


def test_catalog_captures_every_declared_kernel(catalog_reports):
    """Dynamic proof: tracing the catalog captures a KernelLaunchSpec
    for every declared launch name (COVERAGE_GAP findings would fail
    the gate test; this pins the declared set itself so a NEW kernel
    that never joins the catalog is caught too)."""
    from paddle_tpu.analysis.kernel_catalog import ALL_KERNEL_NAMES
    assert ALL_KERNEL_NAMES == {
        "rms_norm_fwd", "rms_norm_bwd", "residual_rms_norm_fwd",
        "layer_norm_fwd", "fused_adamw", "paged_attention_decode",
        "flash_attention_fwd", "flash_attention_bwd_dq",
        "flash_attention_bwd_dkv", "decode_attn_block",
        "decode_mlp_block", "decode_block_fused", "prefill_attn_block",
        "linear_ce_fwd", "linear_ce_bwd_dx", "linear_ce_bwd_dh",
        "swiglu_fwd", "swiglu_bwd"}
    captured = set()
    for r in catalog_reports:
        assert not any(f.code in ("COVERAGE_GAP", "TRACE_ERROR")
                       for f in r.findings), r.to_dict()
        captured.update(r.meta.get("kernels", []))
    assert captured == set(ALL_KERNEL_NAMES)


def test_registry_ops_all_have_lint_metas_and_key_declarations():
    """Every registered op is covered by the registry lint's sample
    metas AND carries a declare_cache_key declaration — an op added
    without either shows up here before it ships."""
    from paddle_tpu.analysis.kernel_catalog import _lint_metas
    metas = _lint_metas()
    assert set(KERNELS.ops()) == set(metas)
    for op in KERNELS.ops():
        assert KERNELS.cache_key_decl(op) is not None, op


# -- rule unit tests on synthetic launches ------------------------------

def _spec(grid, outs, ins=(), scratch=(), accum=(), prefetch=(),
          nsp=0, budget=10 << 20, kernel=None):
    return KernelLaunchSpec(
        name="synthetic", grid=tuple(grid), num_scalar_prefetch=nsp,
        prefetch=tuple(prefetch), inputs=tuple(ins),
        outputs=tuple(outs), scratch=tuple(scratch),
        accum_outputs=tuple(accum), vmem_budget=budget,
        interpret=True, kernel=kernel)


def _op(shape, block, index_map, dtype="float32", space="vmem"):
    return KernelOperand(shape=tuple(shape), dtype=dtype,
                         block_shape=tuple(block) if block else None,
                         index_map=index_map, space=space)


def _codes(findings):
    return sorted(f.code for f in findings)


def test_rule_grid_floor_drop_output_and_input():
    from paddle_tpu.analysis.kernel_rules import check_launch
    # output (128,) in blocks of 32 but the grid only runs 3 steps
    spec = _spec((3,), [_op((128,), (32,), lambda i: (i,))])
    assert _codes(check_launch(spec)) == ["GRID_FLOOR_DROP"]
    # the fused-MLP class: full output, under-read weight input
    spec = _spec((1,),
                 [_op((2, 8), (2, 8), lambda j: (0, 0))],
                 ins=[_op((8, 96), (8, 64), lambda j: (0, j))],
                 accum=(0,))
    found = check_launch(spec)
    assert _codes(found) == ["GRID_FLOOR_DROP"]
    assert found[0].site == "synthetic/in0"
    # divisor grid: silent
    spec = _spec((4,), [_op((128,), (32,), lambda i: (i,))])
    assert check_launch(spec) == []


def test_rule_input_coverage_exempts_scalar_prefetch_launches():
    """Paged kernels read live pages only — data-dependent input
    coverage must not false-positive."""
    from paddle_tpu.analysis.kernel_rules import check_launch
    spec = _spec(
        (2,),
        [_op((2, 4), (1, 4), lambda b, bt: (b, 0))],
        ins=[_op((16, 4), (1, 4), lambda b, bt: (int(bt[b]), 0))],
        prefetch=[((2,), "int32")], nsp=1)
    assert check_launch(spec) == []


def test_rule_oob_block():
    from paddle_tpu.analysis.kernel_rules import check_launch
    # off-by-one index map: block 4 starts at 128 >= extent 128
    spec = _spec((4,), [_op((128,), (32,), lambda i: (i,))],
                 ins=[_op((128,), (32,), lambda i: (i + 1,))])
    assert "OOB_BLOCK" in _codes(check_launch(spec))
    # a partially overhanging LAST block is legal (Pallas masks it)
    spec = _spec((4,), [_op((100,), (32,), lambda i: (i,))])
    assert check_launch(spec) == []


def test_rule_write_race_requires_declared_accumulation():
    from paddle_tpu.analysis.kernel_rules import check_launch
    out = _op((2, 8), (2, 8), lambda j: (0, 0))
    ins = [_op((8, 64), (8, 32), lambda j: (0, j))]
    undeclared = _spec((2,), [out], ins=ins)
    assert _codes(check_launch(undeclared)) == ["WRITE_RACE"]
    declared = _spec((2,), [out], ins=ins, accum=(0,))
    assert check_launch(declared) == []


def test_rule_vmem_overcommit_window_model(monkeypatch):
    from paddle_tpu.analysis.kernel_rules import check_launch
    # 2 varying f32 (1024, 1024) blocks = 2 x 2 x 4MiB = 16MiB, plus a
    # 4MiB scratch -> 20MiB > the 16MiB envelope
    big = lambda: _spec(  # noqa: E731
        (4,),
        [_op((4096, 1024), (1024, 1024), lambda i: (i, 0))],
        ins=[_op((4096, 1024), (1024, 1024), lambda i: (i, 0))],
        scratch=[((1024, 1024), "float32", "vmem")])
    found = check_launch(big())
    assert _codes(found) == ["VMEM_OVERCOMMIT"]
    assert found[0].detail["need_bytes"] == 20 << 20
    # a constant-index block is resident once, not double-buffered:
    # 2 x 4MiB const + 4MiB scratch = 12MiB fits
    const = _spec(
        (4,),
        [_op((1024, 1024), (1024, 1024), lambda i: (0, 0))],
        ins=[_op((1024, 1024), (1024, 1024), lambda i: (0, 0))],
        scratch=[((1024, 1024), "float32", "vmem")], accum=(0,))
    assert check_launch(const) == []
    # an operator-raised fused budget raises the envelope with it
    monkeypatch.setenv("PADDLE_TPU_SCOPED_VMEM_BUDGET", str(32 << 20))
    assert check_launch(big()) == []


def test_rule_vmem_resident_share_in_combined_launches():
    """Combined multi-window launches (the single-launch decode block:
    page operands streamed per grid step, the weight windows + scratch
    resident for the whole launch) must ALSO fit their resident share
    under the per-launch dispatch budget — the streamed double-buffer
    envelope alone would let an oversized resident set sneak through.
    All-resident launches keep the historic envelope-only contract
    (the const spec in the window-model test above)."""
    from paddle_tpu.analysis.kernel_rules import check_launch
    const_w = _op((1024, 1024), (1024, 1024), lambda i: (0, 0))
    streamed = _op((4096, 8), (1024, 8), lambda i: (i, 0))
    spec = _spec((4,), [_op((4, 8), (1, 8), lambda i: (i, 0))],
                 ins=[const_w, const_w, streamed],
                 scratch=[((1024, 1024), "float32", "vmem")])
    found = check_launch(spec)    # 2x4MiB const + 4MiB scratch > 10MiB
    assert _codes(found) == ["VMEM_OVERCOMMIT"]
    assert found[0].site == "synthetic/resident"
    assert found[0].detail["resident_bytes"] == 12 << 20
    # the same launch under a budget that holds its resident share
    roomy = _spec((4,), [_op((4, 8), (1, 8), lambda i: (i, 0))],
                  ins=[const_w, const_w, streamed],
                  scratch=[((1024, 1024), "float32", "vmem")],
                  budget=16 << 20)
    assert check_launch(roomy) == []


def test_rule_vmem_counts_prefetch_streamed_pages_double_buffered():
    """A page operand whose index map derefs the prefetch table
    collapses to page 0 on the all-zero sample — the window model must
    still charge it as streamed (2x double-buffered, probed on the
    ramp sample), or a pipelining kernel sneaks under the envelope."""
    from paddle_tpu.analysis.kernel_rules import check_launch
    page = _op((64, 1024, 1024), (1, 1024, 1024),
               lambda b, bt: (int(bt[b]), 0, 0))       # 4MiB f32 page
    out = _op((4, 8), (1, 8), lambda b, bt: (b, 0))
    spec = _spec((4,), [out], ins=[page, page, page],
                 prefetch=[((4,), "int32")], nsp=1,
                 scratch=[((1024, 1024), "float32", "vmem")])
    found = check_launch(spec)    # 3 pages x2x4MiB + 4MiB scratch
    assert _codes(found) == ["VMEM_OVERCOMMIT"]
    assert found[0].detail["need_bytes"] == (28 << 20) + 64  # + out windows


def test_rule_scratch_mismatch():
    from paddle_tpu.analysis.kernel_rules import check_launch

    def kernel(a_ref, b_ref, o_ref):
        pass

    ok = _spec((1,), [_op((8,), (8,), lambda i: (i,))],
               ins=[_op((8,), (8,), lambda i: (i,))] * 2,
               kernel=kernel)
    assert check_launch(ok) == []
    missing = _spec((1,), [_op((8,), (8,), lambda i: (i,))],
                    ins=[_op((8,), (8,), lambda i: (i,))] * 2,
                    scratch=[((8, 8), "float32", "vmem")],
                    kernel=kernel)             # kernel lacks the scratch ref
    assert _codes(check_launch(missing)) == ["SCRATCH_MISMATCH"]
    empty = _spec((1,), [_op((8,), (8,), lambda i: (i,))],
                  scratch=[((0, 8), "float32", "vmem")])
    assert "SCRATCH_MISMATCH" in _codes(check_launch(empty))


def test_rule_dispatch_key_gap():
    from paddle_tpu.analysis.kernel_rules import dispatch_key_rule
    from paddle_tpu.ops.pallas.registry import KernelRegistry
    reg = KernelRegistry()
    reg.register("op", "fancy", lambda: None, priority=10,
                 supports=lambda m: (m["n"] < 8 and not m["hidden_knob"],
                                     "r"))
    reg.register("op", "plain", lambda: None, priority=0)
    meta = {"n": 4, "hidden_knob": False, "dtype": "float32"}
    # undeclared op -> one finding
    found = dispatch_key_rule(reg, "op", meta)
    assert _codes(found) == ["DISPATCH_KEY_GAP"]
    assert found[0].site == "op:undeclared"
    # declaration missing the hidden knob -> the gap is named
    reg.declare_cache_key("op", ("n", "dtype"))
    found = dispatch_key_rule(reg, "op", meta)
    assert len(found) == 1 and found[0].detail["gap"] == ["hidden_knob"]
    # full declaration (via covers aliasing) -> silent
    reg.declare_cache_key("op", ("n", "dtype", "route"),
                          covers={"hidden_knob": "route"})
    assert dispatch_key_rule(reg, "op", meta) == []


def test_fused_train_key_covers_budget_and_interpret(monkeypatch):
    """The trainer/train-step program caches must key on every
    dispatch input the supports() predicates read — the budget env
    knob included (the _PAGED_CACHE stale-route class)."""
    from paddle_tpu.distributed.trainer import _fused_train_key
    k0 = _fused_train_key()
    monkeypatch.setenv("PADDLE_TPU_FUSED_VMEM_BUDGET", str(1 << 20))
    assert _fused_train_key() != k0


# -- CLI contract (subprocess: fast --case subsets) ---------------------

def test_cli_clean_gate_and_json_schema(tmp_path):
    out_json = str(tmp_path / "findings.json")
    r = _run("--case", "fused_swiglu@tiny", "--json", out_json,
             "--quiet")
    assert r.returncode == 0, r.stderr + r.stdout
    with open(out_json) as fh:
        doc = json.load(fh)
    assert set(doc.keys()) == {"version", "programs", "summary"}
    assert list(doc["programs"]) == ["fused_swiglu@tiny"]
    assert doc["summary"]["findings"] == 0


def test_cli_demo_regression_fails_and_banks_json(tmp_path):
    out_json = str(tmp_path / "findings.json")
    r = _run("--case", "rms_norm@tiny", "--demo-regression",
             "--json", out_json)
    assert r.returncode == 2, r.stderr + r.stdout
    assert "GRID_FLOOR_DROP" in r.stderr
    with open(out_json) as fh:
        doc = json.load(fh)
    assert set(doc["programs"]) == {"rms_norm@tiny",
                                    "demo_prefix_mlp_block@tiny"}


def test_cli_bad_invocations_exit_3_and_list_names_cases():
    # kept in one test: each subprocess pays the full package import
    assert _run("--case", "nope", "--quiet").returncode == 3
    assert _run("--write-baseline", "--demo-regression",
                "--quiet").returncode == 3
    # subset --write-baseline over the SHARED baseline would drop every
    # other case's accepted fingerprints
    assert _run("--case", "rms_norm", "--write-baseline",
                "--quiet").returncode == 3
    names = _run("--list").stdout.split()
    assert "rms_norm@tiny" in names
    assert "decode_attn_block@flagship_serving_int8" in names
    assert "kernel_registry" in names


# -- registry-wide differential sweep (satellite) -----------------------
#
# One parametrized test that sweeps EVERY registered op: the
# pallas_fused variant under interpret vs the priority-0 fallback at
# supports()-boundary shapes (ragged/prime dims, non-divisor tiles,
# hd % 8 edges, the exact VMEM budget edge), asserting numeric parity
# — plus a clean-fallback check that auto dispatch under interpret
# selects the fallback with a human-readable reason.

_RNG = np.random.RandomState(7)


def _f32(*shape):
    return jnp.asarray(_RNG.randn(*shape) * 0.3, jnp.float32)


def _flat(tree):
    return jnp.concatenate(
        [jnp.ravel(t).astype(jnp.float32)
         for t in jax.tree_util.tree_leaves(tree)])


def _diff_rms_norm_bwd():
    x, w, g = _f32(13, 32), _f32(32), _f32(13, 32)   # prime row count
    run = lambda fn: fn(1e-6, (x, w), g)             # noqa: E731
    return run, ("rms_norm_bwd",)


def _diff_rms_norm_residual():
    d, x, w = _f32(13, 32), _f32(13, 32), _f32(32)

    def run(fn):
        return fn(d, x, w, 1e-6, mode=None)
    return run, ("rms_norm_residual",)


def _diff_fused_linear_ce():
    h, w = _f32(12, 32), _f32(32, 100)               # T%8!=0, V%128!=0
    lab = jnp.asarray(
        np.where(_RNG.rand(12) < 0.3, -100, _RNG.randint(0, 100, 12)),
        jnp.int32)

    def run(fn):
        loss, grads = jax.value_and_grad(
            lambda hh, ww: fn(hh, ww, lab), argnums=(0, 1))(h, w)
        return loss, grads
    return run, ("fused_linear_ce",)


def _diff_fused_swiglu():
    g, u = _f32(13, 64), _f32(13, 64)                # ragged rows

    def run(fn):
        out, grads = jax.value_and_grad(
            lambda gg, uu: fn(gg, uu).sum(), argnums=(0, 1))(g, u)
        return out, grads
    return run, ("fused_swiglu",)


def _diff_fused_adamw():
    n = 1000                                          # pad path
    p, g = _f32(n), _f32(n) * 0.01
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)

    def run(fn):
        return fn(p, g, m, v, 1e-3, 3.0, grad_scale=jnp.float32(0.5),
                  shadow_dtype=jnp.bfloat16)
    return run, ("fused_adamw",)


def _decode_inputs(hd=16):
    B, D, H, KV, BS, MB = 2, 32, 2, 2, 8, 3          # MB odd: clamp edge
    N = B * MB + 1
    x, nw = _f32(B, D), jnp.abs(_f32(D)) + 0.5
    wq, wk, wv = _f32(D, H * hd), _f32(D, KV * hd), _f32(D, KV * hd)
    wo = _f32(H * hd, D)
    T = MB * BS + 1
    inv = 1.0 / (10000.0 ** (np.arange(0, hd, 2) / hd))
    ang = np.arange(T)[:, None] * inv[None, :]
    sin = jnp.asarray(np.sin(ang), jnp.float32)
    cos = jnp.asarray(np.cos(ang), jnp.float32)
    kp, vp = _f32(N, BS, KV, hd), _f32(N, BS, KV, hd)
    bt = jnp.asarray(
        _RNG.permutation(N - 1)[: B * MB].reshape(B, MB) + 1, jnp.int32)
    ln = jnp.asarray([5, BS * MB - 1], jnp.int32)    # ragged live pages
    return (x, nw, wq, wk, wv, wo, sin, cos, kp, vp, bt, ln)


def _diff_decode_attn_block():
    args = _decode_inputs()

    def run(fn):
        xo, kn, vn = fn(*args)
        return xo, kn, vn
    return run, ("decode_attn_block",)


def _diff_decode_mlp_block():
    B, D, F = 2, 32, 96                               # no divisor tile
    args = (_f32(B, D), jnp.abs(_f32(D)) + 0.5, _f32(D, F),
            _f32(D, F), _f32(F, D))

    def run(fn):
        return fn(*args)
    return run, ("decode_mlp_block",)


def _diff_decode_block_fused():
    # the single-launch block at the same clamp-edge decode shapes,
    # plus the MLP half on a ragged (non-divisor-tile) intermediate
    (x, nw, wq, wk, wv, wo, sin, cos, kp, vp, bt, ln) = _decode_inputs()
    D, F = 32, 96
    pw = jnp.abs(_f32(D)) + 0.5
    wg, wu, wd = _f32(D, F), _f32(D, F), _f32(F, D)

    def run(fn):
        xo, kn, vn = fn(x, nw, wq, wk, wv, wo, pw, wg, wu, wd, sin,
                        cos, kp, vp, bt, ln)
        return xo, kn, vn
    return run, ("decode_block_fused",)


def _diff_prefill_attn_block():
    # warm mid-page start, ragged valid rows (13 of 16), odd page count
    P, D, H, KV, hd, BS, MB = 16, 32, 4, 2, 16, 8, 5
    N = MB + 3
    x, nw = _f32(P, D), jnp.abs(_f32(D)) + 0.5
    wq, wk, wv = _f32(D, H * hd), _f32(D, KV * hd), _f32(D, KV * hd)
    wo = _f32(H * hd, D)
    pos0, n_valid = 10, 13
    inv = 1.0 / (10000.0 ** (np.arange(0, hd, 2) / hd))
    ang = (pos0 + np.arange(P))[:, None] * inv[None, :]
    sin = jnp.asarray(np.sin(ang), jnp.float32)
    cos = jnp.asarray(np.cos(ang), jnp.float32)
    kp, vp = _f32(N, BS, KV, hd), _f32(N, BS, KV, hd)
    tab = jnp.asarray(_RNG.permutation(N - 1)[:MB] + 1, jnp.int32)

    def run(fn):
        xo, kn, vn = fn(x, nw, wq, wk, wv, wo, sin, cos, kp, vp, tab,
                        jnp.int32(pos0), jnp.int32(n_valid))
        # rows past n_valid of xo are unspecified in the ragged fused
        # kernel (their compute is skipped) — compare the live rows
        return xo[:n_valid], kn, vn
    return run, ("prefill_attn_block",)


def _diff_prefill_mlp_block():
    P, D, F = 16, 32, 96                              # prefill rows
    args = (_f32(P, D), jnp.abs(_f32(D)) + 0.5, _f32(D, F),
            _f32(D, F), _f32(F, D))

    def run(fn):
        return fn(*args)
    return run, ("prefill_mlp_block",)


_DIFF_CASES = {
    "rms_norm_bwd": _diff_rms_norm_bwd,
    "rms_norm_residual": _diff_rms_norm_residual,
    "fused_linear_ce": _diff_fused_linear_ce,
    "fused_swiglu": _diff_fused_swiglu,
    "fused_adamw": _diff_fused_adamw,
    "decode_attn_block": _diff_decode_attn_block,
    "decode_mlp_block": _diff_decode_mlp_block,
    "decode_block_fused": _diff_decode_block_fused,
    "prefill_attn_block": _diff_prefill_attn_block,
    "prefill_mlp_block": _diff_prefill_mlp_block,
}


def test_differential_sweep_covers_every_registered_op():
    """A newly registered op without a differential case fails HERE —
    the sweep cannot silently shrink relative to the registry."""
    assert set(_DIFF_CASES) == set(KERNELS.ops())


@pytest.mark.parametrize("op", sorted(_DIFF_CASES))
def test_pallas_variant_matches_fallback_at_boundary_shapes(op):
    build = _DIFF_CASES[op]
    run, (op_name,) = build()
    # the highest-priority variant is the Pallas one ("pallas_fused"
    # for the per-stage ops, "pallas_block" for the single-launch op)
    pname = KERNELS.variants(op_name)[0].name
    with KERNELS.force(op_name, pname):
        got = run(KERNELS.variant(op_name, pname).fn)
    want = run(KERNELS.variants(op_name)[-1].fn)      # priority-0
    np.testing.assert_allclose(np.asarray(_flat(got), np.float32),
                               np.asarray(_flat(want), np.float32),
                               rtol=5e-5, atol=5e-5,
                               err_msg=f"{op}: pallas(interpret) vs "
                                       "priority-0 fallback diverged")


@pytest.mark.parametrize("op", sorted(_DIFF_CASES))
def test_auto_dispatch_under_interpret_falls_back_with_reason(op):
    """At the supports() boundary (interpret mode is itself the
    hardest boundary off-TPU) auto dispatch must select the priority-0
    fallback and every rejected variant must carry a human-readable
    reason string."""
    from paddle_tpu.analysis.kernel_catalog import _lint_metas
    meta = dict(_lint_metas()[op])
    meta["interpret"] = True
    rows = KERNELS.explain(op, meta)
    selected = [r for r in rows if r["selected"]]
    assert selected and selected[0]["priority"] == 0, rows
    for r in rows:
        assert isinstance(r["reason"], str) and r["reason"], rows


def test_supports_boundary_exact_vmem_budget_edge():
    """The CE predicate flips exactly AT the budget: the worst-case
    window bytes of the first fitting tile are <= budget by
    construction, budget-1 rejects it (with the budget named), and the
    fused_mlp candidate list obeys the same edge."""
    need = ft._ce_vmem_need(128, 256, 2048, 2)
    meta = ft.ce_meta(4096, 2048, 32000, jnp.bfloat16)
    meta["interpret"] = False
    meta["vmem_budget"] = need
    ok, why = ft._supports_ce(meta)
    assert ok, why
    meta["vmem_budget"] = need - 1
    ok, why = ft._supports_ce(meta)
    assert not ok and "VMEM" in why
    # the fused_mlp candidate list obeys the same edge: one byte under
    # the 512-tile's need drops 512 from the fitting list (the next
    # smaller divisor tile takes over as the traced default)
    bneed = fdb._mlp_vmem_need(8, 1024, 2, 512)
    assert fdb._mlp_fitting_candidates(8, 1024, 4096, 2, bneed)[0] == 512
    assert fdb._mlp_fitting_candidates(
        8, 1024, 4096, 2, bneed - 1)[0] == 256


def test_supports_boundary_hd_not_multiple_of_8():
    meta = fdb.decode_meta_dims(2, 32, 2, 2, 20, 96, 8, 4,
                                jnp.float32, jnp.float32, False)
    meta["interpret"] = False
    ok, why = fdb._supports_attn(meta)
    assert not ok and "head_dim" in why and "8" in why
