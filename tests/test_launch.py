"""Launcher / store / flight-recorder tests.

Reference test model: the new-style distributed tests shell out to the real
launcher (test/collective/test_communication_api_base.py:64 —
`python -m paddle.distributed.launch --devices …`), so the production
rendezvous path is exercised. Same here, on CPU.
"""
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu.distributed.store import TCPStore, TCPStoreServer
from paddle_tpu.distributed.flight_recorder import (
    enable_flight_recorder, disable_flight_recorder, get_flight_recorder)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- TCPStore ---------------------------------------------------------------
def test_store_set_get_add_delete():
    srv = TCPStoreServer()
    c = TCPStore("127.0.0.1", srv.port)
    c.set("k", "v1")
    assert c.get("k") == b"v1"
    assert c.get("missing") is None
    assert c.add("ctr", 3) == 3
    assert c.add("ctr", 2) == 5
    c.delete("k")
    assert c.get("k") is None
    assert sorted(c.list_keys("")) == ["ctr"]
    c.close()
    srv.close()


def test_store_wait_and_barrier_two_clients():
    srv = TCPStoreServer()

    def worker():
        c = TCPStore("127.0.0.1", srv.port)
        c.wait("go", timeout=10.0)
        c.barrier("b0", 2, timeout=10.0)
        c.set("done", "1")
        c.close()

    t = threading.Thread(target=worker)
    t.start()
    main = TCPStore("127.0.0.1", srv.port)
    time.sleep(0.2)
    main.set("go", "1")
    main.barrier("b0", 2, timeout=10.0)
    main.wait("done", timeout=10.0)
    t.join(timeout=10)
    assert not t.is_alive()
    with pytest.raises(TimeoutError):
        main.wait("never", timeout=0.3)
    main.close()
    srv.close()


# -- launcher end-to-end ----------------------------------------------------
WORKER_OK = textwrap.dedent("""
    import json, os, sys
    out = os.environ["TEST_OUT_DIR"]
    rank = os.environ["PADDLE_TRAINER_ID"]
    info = {k: os.environ.get(k) for k in
            ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM", "PADDLE_LOCAL_RANK",
             "PADDLE_MASTER", "PADDLE_JOB_ID")}
    with open(os.path.join(out, f"rank{rank}.json"), "w") as f:
        json.dump(info, f)
""")

WORKER_ELASTIC = textwrap.dedent("""
    import os, sys
    # fail on the first job incarnation, succeed after elastic restart
    if os.environ["PADDLE_JOB_ID"] == "0":
        sys.exit(3)
    open(os.path.join(os.environ["TEST_OUT_DIR"],
         "ok" + os.environ["PADDLE_TRAINER_ID"]), "w").write("1")
""")


def _run_launch(tmp_path, worker_src, extra_args, env_extra=None):
    script = tmp_path / "worker.py"
    script.write_text(worker_src)
    env = dict(os.environ, TEST_OUT_DIR=str(tmp_path),
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--log_dir", str(tmp_path / "log")] + extra_args + [str(script)],
        env=env, capture_output=True, text=True, timeout=120)


def test_launch_spawns_ranks_with_env(tmp_path):
    res = _run_launch(tmp_path, WORKER_OK, ["--nproc_per_node", "2"])
    assert res.returncode == 0, res.stderr
    infos = {}
    for r in (0, 1):
        with open(tmp_path / f"rank{r}.json") as f:
            infos[r] = json.load(f)
    assert infos[0]["PADDLE_TRAINERS_NUM"] == "2"
    assert infos[1]["PADDLE_TRAINER_ID"] == "1"
    assert infos[0]["PADDLE_MASTER"].startswith("127.0.0.1:")


def test_launch_elastic_restart(tmp_path):
    res = _run_launch(tmp_path, WORKER_ELASTIC,
                      ["--nproc_per_node", "2", "--elastic_retries", "2"])
    assert res.returncode == 0, res.stderr
    assert (tmp_path / "ok0").exists() and (tmp_path / "ok1").exists()
    assert "elastic restart" in res.stderr


def test_launch_failure_propagates(tmp_path):
    res = _run_launch(tmp_path, "import sys; sys.exit(7)", [])
    assert res.returncode == 7


# -- flight recorder --------------------------------------------------------
def test_flight_recorder_records_and_dumps(tmp_path):
    import paddle_tpu.distributed as dist
    dump = tmp_path / "fr.json"
    rec = enable_flight_recorder(timeout=3600.0, dump_path=str(dump))
    try:
        t = paddle.to_tensor(np.ones((4,), np.float32))
        dist.all_reduce(t)
        dist.broadcast(t, src=0)
        tasks = rec.tasks()
        assert len(tasks) == 2
        assert tasks[0].op == "all_reduce"
        assert tasks[0].shape == (4,)
        assert not tasks[0].pending
        rec.dump(reason="test")
        report = json.loads(dump.read_text())
        assert report["reason"] == "test"
        assert len(report["entries"]) == 2
        # reduce is built on all_reduce: must record ONE logical entry
        dist.reduce(t, dst=0)
        assert [x.op for x in rec.tasks()].count("reduce") == 1
        assert "all_reduce" not in [x.op for x in rec.tasks()[2:]]
        # group passed positionally still records the axis
        from paddle_tpu.distributed.topology import CommGroup
        dist.all_reduce(t, dist.ReduceOp.SUM, CommGroup("mp", [0], 0))
        assert rec.tasks()[-1].axis == "mp"
        # alltoall alias is instrumented; payload tensor shape is captured
        o1 = paddle.to_tensor(np.zeros((2,), np.float32))
        o2 = paddle.to_tensor(np.zeros((2,), np.float32))
        i1 = paddle.to_tensor(np.ones((2,), np.float32))
        i2 = paddle.to_tensor(np.ones((2,), np.float32))
        dist.alltoall([o1, o2], [i1, i2])
        assert rec.tasks()[-1].op == "all_to_all"
        out_lists = [paddle.to_tensor(np.zeros((3,), np.float32))]
        dist.all_gather(out_lists, paddle.to_tensor(
            np.ones((3,), np.float32)))
        assert rec.tasks()[-1].shape == (3,)
    finally:
        disable_flight_recorder()


def test_flight_recorder_disabled_no_overhead():
    import paddle_tpu.distributed as dist
    rec = get_flight_recorder()
    assert not rec.enabled
    t = paddle.to_tensor(np.ones((2,), np.float32))
    dist.all_reduce(t)   # should not record
    assert all(x.op != "all_reduce" or x.end_ts for x in rec.tasks())


class TestElasticManager:
    """Membership + re-rank over the store (reference:
    fleet/elastic/manager.py:126; test pattern:
    test_fleet_elastic_manager.py with a mocked registry)."""

    def _store(self):
        from paddle_tpu.distributed.store import TCPStoreServer, TCPStore
        srv = TCPStoreServer(port=0)
        return srv, TCPStore("127.0.0.1", srv.port)

    def test_membership_and_rerank(self):
        from paddle_tpu.distributed.launch.elastic import ElasticManager
        srv, store = self._store()
        try:
            a = ElasticManager(store, node_id="hostB", min_nodes=1)
            b = ElasticManager(store, node_id="hostA", min_nodes=1)
            a.register()
            b.register()
            # rank order is sorted node id: hostA=0, hostB=1
            n, r = a.resolve(timeout=10, settle=0.3)
            assert (n, r) == (2, 1)
            n, r = b.resolve(timeout=10, settle=0.3)
            assert (n, r) == (2, 0)
        finally:
            srv.close()

    def test_scale_in_detection_and_rerank(self):
        import time as _t
        from paddle_tpu.distributed.launch.elastic import ElasticManager
        srv, store = self._store()
        try:
            a = ElasticManager(store, node_id="n0", min_nodes=1,
                               heartbeat_ttl=0.6)
            b = ElasticManager(store, node_id="n1", min_nodes=1,
                               heartbeat_ttl=0.6)
            a.register()
            b.register()
            assert a.resolve(timeout=10, settle=0.3) == (2, 0)
            # n1 leaves (stops heartbeating)
            b.leave()
            _t.sleep(0.1)
            assert a.scale_event() == "scale_in"
            n, r = a.resolve(timeout=10, settle=0.3)
            assert (n, r) == (1, 0)
            # n1 rejoins -> scale_out
            b.heartbeat()
            assert a.scale_event() == "scale_out"
            assert a.resolve(timeout=10, settle=0.3) == (2, 0)
        finally:
            srv.close()

    def test_bounds_block_resolution(self):
        from paddle_tpu.distributed.launch.elastic import ElasticManager
        srv, store = self._store()
        try:
            a = ElasticManager(store, node_id="solo", min_nodes=2)
            a.register()
            import pytest as _pytest
            with _pytest.raises(TimeoutError):
                a.resolve(timeout=1.5)
        finally:
            srv.close()


# -- elastic end-to-end -----------------------------------------------------
def test_elastic_end_to_end(tmp_path):
    """VERDICT r4 Next #6 — the full failover loop through the REAL
    stack: 4 single-trainer nodes train a GSPMD-sharded model over gloo;
    the node-3 trainer dies hard mid-run; the surviving controllers
    detect the stale heartbeat, re-rank via the ElasticManager to a
    3-node world, respawn, and the workers resume from the 4-way-sharded
    distributed checkpoint loaded onto the 3-device mesh
    (reshard-on-load). The resumed trajectory must exactly continue the
    pre-crash one. Reference: fleet/elastic/manager.py:126 (watch ->
    re-rank -> relaunch) + checkpoint/load_state_dict.py:526."""
    import socket as _socket
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    worker = os.path.join(REPO, "tests", "elastic_worker.py")
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "PADDLE_ELASTIC_MIN": "2", "PADDLE_ELASTIC_MAX": "4",
        "PADDLE_HEARTBEAT_INTERVAL": "0.5",
        "PADDLE_HEARTBEAT_STALE": "3",
        "PADDLE_ELASTIC_TTL": "5", "PADDLE_ELASTIC_SETTLE": "2",
        "ELASTIC_VICTIM": "3",
    })
    procs = []
    for node in range(4):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "4", "--node_rank", str(node),
             "--nproc_per_node", "1",
             "--master", f"127.0.0.1:{port}",
             "--elastic_retries", "0" if node == 3 else "2",
             "--log_dir", str(tmp_path / f"log{node}"),
             worker, str(out_dir)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = {}
    try:
        # generous bound: ~52s standalone, but xdist runs this next to
        # other multi-process tests on a shared box
        for node, p in enumerate(procs):
            outs[node] = p.communicate(timeout=420)[0]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    logs = "\n\n".join(f"== node {n} ==\n{o[-3000:]}"
                       for n, o in outs.items())
    # victim node fails; survivors finish clean after the re-ranked run
    assert procs[3].returncode != 0, logs
    for node in range(3):
        assert procs[node].returncode == 0, logs

    results = {}
    for r in range(3):
        f = out_dir / f"rank{r}_job1.json"
        assert f.exists(), f"rank {r} job 1 wrote no result\n{logs}"
        results[r] = json.loads(f.read_text())
    for r, res in results.items():
        assert res["world"] == 3, logs
        assert res["start"] == 5, (res, logs)  # resumed, not restarted

    # the resumed trajectory must exactly continue deterministic GD
    import importlib.util
    spec = importlib.util.spec_from_file_location("elastic_worker", worker)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    COLS, CRASH_STEP, LR, N, ROWS, TOTAL_STEPS = (
        mod.COLS, mod.CRASH_STEP, mod.LR, mod.N, mod.ROWS,
        mod.TOTAL_STEPS)
    rng = np.random.RandomState(0)
    A = rng.randn(N, ROWS).astype(np.float32)
    b = rng.randn(N, COLS).astype(np.float32)
    w = rng.randn(ROWS, COLS).astype(np.float32) * 0.1
    losses = []
    for _ in range(TOTAL_STEPS):
        r_ = A @ w - b
        losses.append(float((r_ ** 2).mean()))
        w = w - LR * (2.0 / N / COLS) * (A.T @ r_)
    np.testing.assert_allclose(results[0]["losses"],
                               losses[CRASH_STEP:], rtol=1e-3,
                               err_msg=logs[-1500:])
    assert results[0]["losses"][-1] < losses[CRASH_STEP - 1], \
        "loss did not keep descending after failover"
    # reassemble the 3-way-sharded final weights from per-rank shards
    w_got = np.zeros_like(w)
    for res in results.values():
        off = res["w_offset"]
        loc = np.asarray(res["w_local"], np.float32)
        w_got[off:off + loc.shape[0]] = loc
    np.testing.assert_allclose(w_got, w, rtol=1e-3, atol=1e-5)
