"""Launcher / store / flight-recorder tests.

Reference test model: the new-style distributed tests shell out to the real
launcher (test/collective/test_communication_api_base.py:64 —
`python -m paddle.distributed.launch --devices …`), so the production
rendezvous path is exercised. Same here, on CPU.
"""
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu.distributed.store import TCPStore, TCPStoreServer
from paddle_tpu.distributed.flight_recorder import (
    enable_flight_recorder, disable_flight_recorder, get_flight_recorder)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- TCPStore ---------------------------------------------------------------
def test_store_set_get_add_delete():
    srv = TCPStoreServer()
    c = TCPStore("127.0.0.1", srv.port)
    c.set("k", "v1")
    assert c.get("k") == b"v1"
    assert c.get("missing") is None
    assert c.add("ctr", 3) == 3
    assert c.add("ctr", 2) == 5
    c.delete("k")
    assert c.get("k") is None
    assert sorted(c.list_keys("")) == ["ctr"]
    c.close()
    srv.close()


def test_store_wait_and_barrier_two_clients():
    srv = TCPStoreServer()

    def worker():
        c = TCPStore("127.0.0.1", srv.port)
        c.wait("go", timeout=10.0)
        c.barrier("b0", 2, timeout=10.0)
        c.set("done", "1")
        c.close()

    t = threading.Thread(target=worker)
    t.start()
    main = TCPStore("127.0.0.1", srv.port)
    time.sleep(0.2)
    main.set("go", "1")
    main.barrier("b0", 2, timeout=10.0)
    main.wait("done", timeout=10.0)
    t.join(timeout=10)
    assert not t.is_alive()
    with pytest.raises(TimeoutError):
        main.wait("never", timeout=0.3)
    main.close()
    srv.close()


# -- launcher end-to-end ----------------------------------------------------
WORKER_OK = textwrap.dedent("""
    import json, os, sys
    out = os.environ["TEST_OUT_DIR"]
    rank = os.environ["PADDLE_TRAINER_ID"]
    info = {k: os.environ.get(k) for k in
            ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM", "PADDLE_LOCAL_RANK",
             "PADDLE_MASTER", "PADDLE_JOB_ID")}
    with open(os.path.join(out, f"rank{rank}.json"), "w") as f:
        json.dump(info, f)
""")

WORKER_ELASTIC = textwrap.dedent("""
    import os, sys
    # fail on the first job incarnation, succeed after elastic restart
    if os.environ["PADDLE_JOB_ID"] == "0":
        sys.exit(3)
    open(os.path.join(os.environ["TEST_OUT_DIR"],
         "ok" + os.environ["PADDLE_TRAINER_ID"]), "w").write("1")
""")


def _run_launch(tmp_path, worker_src, extra_args, env_extra=None):
    script = tmp_path / "worker.py"
    script.write_text(worker_src)
    env = dict(os.environ, TEST_OUT_DIR=str(tmp_path),
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--log_dir", str(tmp_path / "log")] + extra_args + [str(script)],
        env=env, capture_output=True, text=True, timeout=120)


def test_launch_spawns_ranks_with_env(tmp_path):
    res = _run_launch(tmp_path, WORKER_OK, ["--nproc_per_node", "2"])
    assert res.returncode == 0, res.stderr
    infos = {}
    for r in (0, 1):
        with open(tmp_path / f"rank{r}.json") as f:
            infos[r] = json.load(f)
    assert infos[0]["PADDLE_TRAINERS_NUM"] == "2"
    assert infos[1]["PADDLE_TRAINER_ID"] == "1"
    assert infos[0]["PADDLE_MASTER"].startswith("127.0.0.1:")


def test_launch_elastic_restart(tmp_path):
    res = _run_launch(tmp_path, WORKER_ELASTIC,
                      ["--nproc_per_node", "2", "--elastic_retries", "2"])
    assert res.returncode == 0, res.stderr
    assert (tmp_path / "ok0").exists() and (tmp_path / "ok1").exists()
    assert "elastic restart" in res.stderr


def test_launch_failure_propagates(tmp_path):
    res = _run_launch(tmp_path, "import sys; sys.exit(7)", [])
    assert res.returncode == 7


# -- flight recorder --------------------------------------------------------
def test_flight_recorder_records_and_dumps(tmp_path):
    import paddle_tpu.distributed as dist
    dump = tmp_path / "fr.json"
    rec = enable_flight_recorder(timeout=3600.0, dump_path=str(dump))
    try:
        t = paddle.to_tensor(np.ones((4,), np.float32))
        dist.all_reduce(t)
        dist.broadcast(t, src=0)
        tasks = rec.tasks()
        assert len(tasks) == 2
        assert tasks[0].op == "all_reduce"
        assert tasks[0].shape == (4,)
        assert not tasks[0].pending
        rec.dump(reason="test")
        report = json.loads(dump.read_text())
        assert report["reason"] == "test"
        assert len(report["entries"]) == 2
        # reduce is built on all_reduce: must record ONE logical entry
        dist.reduce(t, dst=0)
        assert [x.op for x in rec.tasks()].count("reduce") == 1
        assert "all_reduce" not in [x.op for x in rec.tasks()[2:]]
        # group passed positionally still records the axis
        from paddle_tpu.distributed.topology import CommGroup
        dist.all_reduce(t, dist.ReduceOp.SUM, CommGroup("mp", [0], 0))
        assert rec.tasks()[-1].axis == "mp"
        # alltoall alias is instrumented; payload tensor shape is captured
        o1 = paddle.to_tensor(np.zeros((2,), np.float32))
        o2 = paddle.to_tensor(np.zeros((2,), np.float32))
        i1 = paddle.to_tensor(np.ones((2,), np.float32))
        i2 = paddle.to_tensor(np.ones((2,), np.float32))
        dist.alltoall([o1, o2], [i1, i2])
        assert rec.tasks()[-1].op == "all_to_all"
        out_lists = [paddle.to_tensor(np.zeros((3,), np.float32))]
        dist.all_gather(out_lists, paddle.to_tensor(
            np.ones((3,), np.float32)))
        assert rec.tasks()[-1].shape == (3,)
    finally:
        disable_flight_recorder()


def test_flight_recorder_disabled_no_overhead():
    import paddle_tpu.distributed as dist
    rec = get_flight_recorder()
    assert not rec.enabled
    t = paddle.to_tensor(np.ones((2,), np.float32))
    dist.all_reduce(t)   # should not record
    assert all(x.op != "all_reduce" or x.end_ts for x in rec.tasks())


class TestElasticManager:
    """Membership + re-rank over the store (reference:
    fleet/elastic/manager.py:126; test pattern:
    test_fleet_elastic_manager.py with a mocked registry)."""

    def _store(self):
        from paddle_tpu.distributed.store import TCPStoreServer, TCPStore
        srv = TCPStoreServer(port=0)
        return srv, TCPStore("127.0.0.1", srv.port)

    def test_membership_and_rerank(self):
        from paddle_tpu.distributed.launch.elastic import ElasticManager
        srv, store = self._store()
        try:
            a = ElasticManager(store, node_id="hostB", min_nodes=1)
            b = ElasticManager(store, node_id="hostA", min_nodes=1)
            a.register()
            b.register()
            # rank order is sorted node id: hostA=0, hostB=1
            n, r = a.resolve(timeout=10)
            assert (n, r) == (2, 1)
            n, r = b.resolve(timeout=10)
            assert (n, r) == (2, 0)
        finally:
            srv.close()

    def test_scale_in_detection_and_rerank(self):
        import time as _t
        from paddle_tpu.distributed.launch.elastic import ElasticManager
        srv, store = self._store()
        try:
            a = ElasticManager(store, node_id="n0", min_nodes=1,
                               heartbeat_ttl=0.6)
            b = ElasticManager(store, node_id="n1", min_nodes=1,
                               heartbeat_ttl=0.6)
            a.register()
            b.register()
            assert a.resolve(timeout=10) == (2, 0)
            # n1 leaves (stops heartbeating)
            b.leave()
            _t.sleep(0.1)
            assert a.scale_event() == "scale_in"
            n, r = a.resolve(timeout=10)
            assert (n, r) == (1, 0)
            # n1 rejoins -> scale_out
            b.heartbeat()
            assert a.scale_event() == "scale_out"
            assert a.resolve(timeout=10) == (2, 0)
        finally:
            srv.close()

    def test_bounds_block_resolution(self):
        from paddle_tpu.distributed.launch.elastic import ElasticManager
        srv, store = self._store()
        try:
            a = ElasticManager(store, node_id="solo", min_nodes=2)
            a.register()
            import pytest as _pytest
            with _pytest.raises(TimeoutError):
                a.resolve(timeout=1.5)
        finally:
            srv.close()
