"""Lifecycle model checker: the shared invariant hooks on the real
allocator/cache classes, exhaustive small-scope exploration of the
page/slot/COW/spill/handoff state machine, the two demo-regression
bugs, fuzz determinism, counterexample replay, the CLI gate contract,
and the bench pre-step wiring."""
import copy
import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.lifecycle

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "lifecycle_audit.py")
COMMITTED_BASELINE = os.path.join(REPO, "LIFECYCLE_BASELINE.json")

from paddle_tpu.analysis import lifecycle as lc            # noqa: E402
from paddle_tpu.inference.prefix_cache import PrefixCache  # noqa: E402
from paddle_tpu.ops.paged_attention import BlockManager    # noqa: E402


def _run(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, CLI, *args],
                          capture_output=True, text=True, env=env,
                          timeout=600)


# -- satellite: shared .check() invariant hooks on the REAL classes ---

def test_blockmanager_check_clean_through_lifecycle():
    mgr = BlockManager(num_blocks=6, block_size=2, max_blocks_per_seq=8)
    mgr.allocate(1, 3)
    mgr.attach(2, mgr.tables[1][:1])            # share page, incref
    assert mgr.check() == []
    mgr.release(1)
    mgr.release(2)
    assert mgr.check() == []
    assert len(mgr.free) == 6


def test_blockmanager_check_detects_seeded_corruption():
    mgr = BlockManager(num_blocks=6, block_size=2, max_blocks_per_seq=8)
    mgr.allocate(1, 4)
    p = mgr.tables[1][0]
    mgr.refcount[p] = 0          # table still references p: leak + over-share
    problems = mgr.check(raise_on_violation=False)
    assert any("leaked" in m for m in problems)
    assert any("table references" in m for m in problems)
    with pytest.raises(RuntimeError, match="BlockManager.check failed"):
        mgr.check()
    # duplicate free-list entry is its own violation class
    mgr2 = BlockManager(num_blocks=4, block_size=2, max_blocks_per_seq=8)
    mgr2.free.append(mgr2.free[-1])
    assert any("twice" in m
               for m in mgr2.check(raise_on_violation=False))


def test_blockmanager_refcount_never_negative():
    mgr = BlockManager(num_blocks=4, block_size=2, max_blocks_per_seq=8)
    page = mgr.alloc_page()
    assert mgr.decref(page) is True
    with pytest.raises(RuntimeError, match="negative"):
        mgr.decref(page)


def test_prefix_cache_check_clean_and_corrupt():
    mgr = BlockManager(num_blocks=8, block_size=2, max_blocks_per_seq=8)
    pc = PrefixCache(mgr, block_size=2, copy_page=lambda s, d: None)
    mgr.allocate(1, 4)
    pc.insert((1, 2, 3, 4), mgr.tables[1])
    mgr.release(1)                       # tree keeps the pages alive
    assert pc.check() == []
    pc._host_pages += 1                  # seed an offload-counter drift
    problems = pc.check(raise_on_violation=False)
    assert any("host_pages counter" in m for m in problems)
    with pytest.raises(RuntimeError, match="PrefixCache.check failed"):
        pc.check()


def test_engine_per_step_selfcheck_env_hook(monkeypatch):
    """PADDLE_TPU_CHECK_INVARIANTS=1 arms the engines' per-step
    mgr/pcache .check() — a clean drain must not raise."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.inference.generation import GenerationConfig
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import llama
    monkeypatch.setenv("PADDLE_TPU_CHECK_INVARIANTS", "1")
    cfg = llama.LlamaConfig(vocab_size=61, hidden_size=32,
                            intermediate_size=64, num_hidden_layers=1,
                            num_attention_heads=2, num_key_value_heads=2,
                            max_position_embeddings=64,
                            dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(params, cfg, capacity=2, block_size=4,
                        prefill_buckets=(16,), max_seq_len=32)
    assert eng._check_inv is True
    ids = np.random.RandomState(0).randint(0, 61, (5,)).astype(np.int32)
    req = eng.submit(ids, GenerationConfig(max_new_tokens=4, greedy=True))
    eng.drain()
    assert req.output_ids is not None


# -- the model itself -------------------------------------------------

def test_make_world_rejects_request_that_cannot_fit():
    sc = lc.Scope(name="too_big",
                  requests=(lc.ReqSpec((1, 2, 3, 4, 5, 6), max_new=2),),
                  capacity=1, num_blocks=3, block_size=2)
    with pytest.raises(ValueError, match="trivial deadlock"):
        lc.make_world(sc)


@pytest.mark.parametrize("name", sorted(lc.SCOPES))
def test_catalog_scope_explores_clean_reduced(name):
    """Every committed scope stays invariant-clean. Fast tier: a
    truncated prefix of the state space; the slow tier + CLI gate run
    the exhaustive catalog."""
    res = lc.explore(lc.SCOPES[name], max_states=2000)
    assert res.report.findings == []
    assert res.states > 50
    assert res.report.meta["mode"] in ("colocated", "disagg")


@pytest.mark.slow
def test_exhaustive_catalog_meets_scale_budget():
    """Acceptance bound: the full catalog explores >= 10^4 distinct
    states, untruncated, clean, in under 60 s."""
    total_states = total_wall = 0
    for sc in lc.SCOPES.values():
        res = lc.explore(sc)
        assert res.report.findings == [], sc.name
        assert not res.truncated, sc.name
        total_states += res.states
        total_wall += res.wall_s
    assert total_states >= 10_000
    assert total_wall < 60.0


def test_demo_starved_head_deadlocks_with_short_trace():
    sc = lc.DEMO_SCOPES["demo_starved_head"]
    res = lc.explore(sc)
    codes = {f.code for f in res.report.findings}
    assert "DEADLOCK" in codes
    f = next(f for f in res.report.findings if f.code == "DEADLOCK")
    assert len(f.detail["trace"]) <= 25
    assert f.detail["injected_bug"] == "starved_head"
    # replay: the trace lands in a wedged state — requests still
    # pending, but no enabled action makes progress
    world, problems = lc.replay_trace(sc, f.detail["trace"])
    assert problems == []            # deadlock is a progress property
    assert world.pending()
    for action in world.actions():
        child = copy.deepcopy(world)
        changed, _ = child.apply(action)
        assert not changed, f"action {action} escaped the deadlock"


def test_demo_abort_leak_found_and_replayable():
    sc = lc.DEMO_SCOPES["demo_abort_leak"]
    res = lc.explore(sc)
    f = next(f for f in res.report.findings if f.code == "ABORT_LEAK")
    assert len(f.detail["trace"]) <= 25
    assert f.fingerprint.startswith(
        "lifecycle_demo_abort_leak::lifecycle::ABORT_LEAK::")
    assert f.severity == "error" and f.rule == "lifecycle"
    world, problems = lc.replay_trace(sc, f.detail["trace"])
    assert any(code == "ABORT_LEAK" for code, _, _ in problems)


def test_fuzz_is_deterministic_byte_for_byte():
    sc = lc.SCOPES["coloc_nocache"]
    a = lc.fuzz(sc, 20, seed=11)
    b = lc.fuzz(sc, 20, seed=11)
    assert a.transitions == b.transitions
    assert [f.detail for f in a.report.findings] \
        == [f.detail for f in b.report.findings]
    assert a.report.findings == [] and b.report.findings == []


# -- the gate: committed baseline + CLI contract ----------------------

def test_committed_baseline_holds_zero_findings():
    with open(COMMITTED_BASELINE) as fh:
        doc = json.load(fh)
    assert doc["findings"] == {}
    assert doc["version"] == 1


def test_cli_gate_clean_vs_committed_baseline():
    p = _run("--max-states", "1500", "--quiet")
    assert p.returncode == 0, p.stderr


def test_cli_fuzz_mode_clean():
    p = _run("--fuzz", "5", "--seed", "3", "--scope", "coloc_spill")
    assert p.returncode == 0, p.stderr
    assert "walk(s)" in p.stdout


def test_cli_demo_regression_fails_gate_with_traces(tmp_path):
    doc_path = str(tmp_path / "doc.json")
    p = _run("--scope", "demo_starved_head", "--scope",
             "demo_abort_leak", "--demo-regression", "--json", doc_path)
    assert p.returncode == 2, p.stdout + p.stderr
    assert "GATE FAILED" in p.stderr
    with open(doc_path) as fh:
        doc = json.load(fh)
    by_code = {f["code"] for r in doc["programs"].values()
               for f in r["findings"]}
    assert {"DEADLOCK", "ABORT_LEAK"} <= by_code
    for r in doc["programs"].values():
        for f in r["findings"]:
            assert len(f["detail"]["trace"]) <= 25


def test_cli_dump_dir_writes_flight_recorder_counterexample(tmp_path):
    d = str(tmp_path / "ce")
    p = _run("--scope", "demo_abort_leak", "--demo-regression",
             "--no-baseline", "--dump-dir", d)
    assert p.returncode == 2
    dumps = sorted(os.listdir(d))
    assert dumps and dumps[0] == "lifecycle_ce_0.json"
    with open(os.path.join(d, dumps[0])) as fh:
        dump = json.load(fh)
    assert dump["reason"].startswith("lifecycle:")
    assert dump["fingerprint"].startswith("lifecycle_demo_abort_leak::")
    assert dump["injected_bug"] == "abort_leak"
    assert dump["timeline_tail"]          # one entry per trace action
    assert all(e["event"] == "action" for e in dump["timeline_tail"])


def test_cli_refusal_and_bad_invocation_exit_3():
    p = _run("--write-baseline", "--demo-regression")
    assert p.returncode == 3 and "refusing" in p.stderr
    p = _run("--write-baseline", "--scope", "coloc_spill")
    assert p.returncode == 3 and "refusing" in p.stderr
    p = _run("--scope", "no_such_scope")
    assert p.returncode == 3 and "unknown scope" in p.stderr


def test_cli_list_names_catalog_and_demos():
    p = _run("--list")
    assert p.returncode == 0
    for name in list(lc.SCOPES) + list(lc.DEMO_SCOPES):
        assert name in p.stdout
    assert "demo" in p.stdout and "bug=" in p.stdout


# -- chaining: program-audit --all and the bench pre-step -------------

def test_bench_lifecycle_pre_step_opt_out(monkeypatch):
    sys.path.insert(0, REPO)
    import bench
    monkeypatch.setenv("BENCH_LIFECYCLE", "0")
    out = {}
    bench._lifecycle_audit(out)
    assert out == {}                     # opt-out leaves no marker


@pytest.mark.slow
def test_bench_lifecycle_pre_step_banks_rc(monkeypatch):
    sys.path.insert(0, REPO)
    import bench
    monkeypatch.delenv("BENCH_LIFECYCLE", raising=False)
    out = {}
    bench._lifecycle_audit(out)
    assert out["lifecycle_audit"]["rc"] == 0
    assert out["lifecycle_audit"]["summary"]["findings"] == 0


@pytest.mark.slow
def test_program_audit_all_chains_lifecycle_gate():
    tool = os.path.join(REPO, "tools", "program_audit.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run([sys.executable, tool, "--all"],
                       capture_output=True, text=True, env=env,
                       timeout=1200)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "[lifecycle]" in p.stdout     # the chained gate actually ran
