"""LLaMA flagship tests (BASELINE config 3 path)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.models.llama import (LlamaConfig, init_params, forward,
                                     loss_fn, param_shardings, LLAMA_TINY)
from paddle_tpu.distributed.trainer import MeshConfig, Trainer, make_mesh


CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=64,
                  dtype=jnp.float32, remat=False)


class TestFunctionalLlama:
    @pytest.mark.slow
    def test_forward_shape_and_finite(self):
        params = init_params(CFG, jax.random.key(0))
        tokens = jnp.zeros((2, 8), jnp.int32)
        logits = forward(params, tokens, CFG)
        assert logits.shape == (2, 8, 128)
        assert np.isfinite(np.asarray(logits)).all()

    def test_causality(self):
        """Changing a future token must not affect earlier logits."""
        params = init_params(CFG, jax.random.key(0))
        rng = np.random.RandomState(0)
        t1 = rng.randint(0, 128, (1, 8)).astype(np.int32)
        t2 = t1.copy()
        t2[0, -1] = (t2[0, -1] + 1) % 128
        l1 = np.asarray(forward(params, jnp.asarray(t1), CFG))
        l2 = np.asarray(forward(params, jnp.asarray(t2), CFG))
        np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], atol=1e-5)
        assert not np.allclose(l1[0, -1], l2[0, -1])

    @pytest.mark.slow
    def test_gqa_matches_full_heads_shape(self):
        cfg_full = LlamaConfig(**{**CFG.__dict__, "num_key_value_heads": 4})
        params = init_params(cfg_full, jax.random.key(0))
        logits = forward(params, jnp.zeros((1, 4), jnp.int32), cfg_full)
        assert logits.shape == (1, 4, 128)

    @pytest.mark.slow
    def test_loss_decreases_under_training(self):
        params = init_params(CFG, jax.random.key(0))
        mesh = make_mesh(MeshConfig(), devices=jax.devices()[:1])
        trainer = Trainer(lambda p, t, l: loss_fn(p, t, l, CFG), mesh,
                          param_shardings(mesh, CFG),
                          data_spec=P(), lr=1e-2)
        state = trainer.init_state(params)
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, 128, (4, 16)), jnp.int32)
        labels = tokens  # memorise identity mapping
        losses = []
        for _ in range(5):
            state, m = trainer.step(state, tokens, labels)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses

    @pytest.mark.slow
    def test_remat_same_loss(self):
        cfg_r = LlamaConfig(**{**CFG.__dict__, "remat": True})
        params = init_params(CFG, jax.random.key(0))
        tokens = jnp.asarray(np.random.RandomState(1).randint(
            0, 128, (2, 8)), jnp.int32)
        l1 = loss_fn(params, tokens, tokens, CFG)
        l2 = loss_fn(params, tokens, tokens, cfg_r)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)


class TestShardedLlama:
    @pytest.mark.slow
    def test_sharded_matches_single_device(self):
        """The SPMD-partitioned step must equal the single-device step."""
        params = init_params(CFG, jax.random.key(0))
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, 128, (4, 16)), jnp.int32)
        labels = jnp.asarray(rng.randint(0, 128, (4, 16)), jnp.int32)

        mesh1 = make_mesh(MeshConfig(), devices=jax.devices()[:1])
        t1 = Trainer(lambda p, t, l: loss_fn(p, t, l, CFG), mesh1,
                     param_shardings(mesh1, CFG), data_spec=P(), lr=1e-3,
                     donate=False)
        s1 = t1.init_state(init_params(CFG, jax.random.key(0)))
        s1, m1 = t1.step(s1, tokens, labels)

        mesh8 = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2),
                          devices=jax.devices()[:8])
        t8 = Trainer(lambda p, t, l: loss_fn(p, t, l, CFG), mesh8,
                     param_shardings(mesh8, CFG),
                     data_spec=P(("dp", "fsdp")), lr=1e-3, donate=False)
        s8 = t8.init_state(init_params(CFG, jax.random.key(0)))
        s8, m8 = t8.step(s8, tokens, labels)

        np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]),
                                   rtol=1e-5)
        w1 = np.asarray(s1.params["layers"]["q_proj"])
        w8 = np.asarray(s8.params["layers"]["q_proj"])
        np.testing.assert_allclose(w1, w8, rtol=1e-4, atol=1e-5)

    def test_param_shardings_cover_tree(self):
        mesh = make_mesh(MeshConfig(fsdp=2, tp=2, dp=2),
                         devices=jax.devices()[:8])
        params = init_params(CFG, jax.random.key(0))
        specs = param_shardings(mesh, CFG)
        jax.tree_util.tree_map(lambda p, s: None, params, specs)  # same tree

    @pytest.mark.slow
    def test_grad_accumulation(self):
        params = init_params(CFG, jax.random.key(0))
        mesh = make_mesh(MeshConfig(), devices=jax.devices()[:1])
        tr = Trainer(lambda p, t, l: loss_fn(p, t, l, CFG), mesh,
                     param_shardings(mesh, CFG), data_spec=P(),
                     lr=1e-3, accumulate_steps=2)
        state = tr.init_state(params)
        rng = np.random.RandomState(0)
        # [accum, micro_batch, seq]
        tokens = jnp.asarray(rng.randint(0, 128, (2, 2, 16)), jnp.int32)
        state, m = tr.step(state, tokens, tokens)
        assert np.isfinite(float(m["loss"]))


class TestLlamaLayerAPI:
    @pytest.mark.slow
    def test_layer_model_forward_backward(self):
        from paddle_tpu.models.llama import LlamaForCausalLM
        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=1,
                          num_attention_heads=2, num_key_value_heads=2,
                          dtype=jnp.float32)
        model = LlamaForCausalLM(cfg)
        ids = paddle.to_tensor(np.random.randint(0, 64, (2, 8)))
        loss, logits = model(ids, labels=ids)
        assert logits.shape == [2, 8, 64]
        loss.backward()
        grads = [p.grad for p in model.parameters()]
        assert all(g is not None for g in grads)


class TestDryrun:
    @pytest.mark.slow
    @pytest.mark.parametrize("n", [1, 2, 4, 8])
    def test_dryrun_sizes(self, n):
        from paddle_tpu.distributed.dryrun import run_dryrun
        run_dryrun(n)

    @pytest.fixture
    def _restore_platform_state(self):
        """resolve_devices(force_cpu=False) may mutate process globals
        (JAX_PLATFORMS, jax_platforms config, Pallas force-interpret) when
        it falls back; restore them so later tests see clean state."""
        import os
        import jax
        from paddle_tpu.ops.pallas import _util as pallas_util
        prev_env = os.environ.get("JAX_PLATFORMS")
        prev_cfg = jax.config.jax_platforms
        prev_interp = pallas_util._FORCE_INTERPRET
        yield
        pallas_util.set_force_interpret(prev_interp)
        if prev_env is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = prev_env
        try:
            jax.config.update("jax_platforms", prev_cfg)
        except Exception:
            pass

    @pytest.mark.slow
    def test_resolve_devices_probe_path(self, _restore_platform_state):
        """force_cpu=False probes the default backend in a subprocess.
        The child re-runs sitecustomize, so its default platform (and
        health) is the machine's real accelerator — which may legitimately
        be wedged. Either way the call must return n devices promptly:
        default backend when the probe passes, CPU fallback otherwise."""
        from paddle_tpu.distributed.dryrun import resolve_devices
        devices, reason = resolve_devices(2, force_cpu=False,
                                          probe_timeout=10.0)
        assert len(devices) == 2
        if reason is not None:  # probe failed -> must be the CPU fallback
            assert all(d.platform == "cpu" for d in devices)

    def test_resolve_devices_probe_timeout_falls_back(
            self, _restore_platform_state):
        """A hung/slow probe (simulated with a tiny timeout) must not hang
        the caller — it falls back to the forced virtual CPU mesh."""
        from paddle_tpu.distributed.dryrun import resolve_devices
        devices, reason = resolve_devices(2, force_cpu=False,
                                          probe_timeout=0.01)
        assert reason is not None and len(devices) == 2
        assert all(d.platform == "cpu" for d in devices)


@pytest.mark.slow
def test_trainer_nan_watch():
    """check_nan_inf catches non-finite loss inside the compiled
    hybrid-parallel step."""
    import jax.numpy as jnp
    from paddle_tpu.core.flags import GLOBAL_FLAGS
    from paddle_tpu.models.llama import init_params, param_shardings

    mesh = make_mesh(MeshConfig())
    params = init_params(CFG, jax.random.PRNGKey(0))

    def poisoned(p, t, l):
        return loss_fn(p, t, l, CFG) + jnp.log(jnp.float32(-1.0))

    GLOBAL_FLAGS.set("check_nan_inf", True)
    try:
        tr = Trainer(poisoned, mesh, param_shardings(mesh, CFG), lr=1e-4)
        state = tr.init_state(params)
        toks = jnp.zeros((2, 16), jnp.int32)
        import pytest as _pytest
        with _pytest.raises(FloatingPointError, match="check_nan_inf"):
            tr.step(state, toks, toks)
    finally:
        GLOBAL_FLAGS.set("check_nan_inf", False)


@pytest.mark.slow
def test_fused_linear_cross_entropy_matches_unfused():
    """Chunked lm-head+CE (Liger-style) must match the materialized
    logits path in value and gradient."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.llama import (LlamaConfig, init_params, loss_fn,
                                         forward)
    from paddle_tpu.models._common import (masked_cross_entropy,
                                           fused_linear_cross_entropy)

    cfg = LlamaConfig(vocab_size=503, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 503, (2, 33)),
                       jnp.int32)
    labels = jnp.roll(toks, -1, 1).at[:, -1].set(-1)
    fused = float(loss_fn(params, toks, labels, cfg))
    unfused = float(masked_cross_entropy(forward(params, toks, cfg),
                                         labels))
    assert abs(fused - unfused) < 1e-4
    gf = jax.grad(lambda p: loss_fn(p, toks, labels, cfg))(params)
    gu = jax.grad(lambda p: masked_cross_entropy(
        forward(p, toks, cfg), labels))(params)
    mx = max(jax.tree_util.tree_leaves(jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a.astype(jnp.float32) -
                                   b.astype(jnp.float32)).max()), gf, gu)))
    assert mx < 2e-2  # bf16 params

    # helper with odd T / small chunks
    h = jnp.asarray(np.random.randn(7, 16), jnp.float32)
    hd = jnp.asarray(np.random.randn(16, 29), jnp.float32)
    lb = jnp.asarray(np.random.randint(-1, 29, (7,)), jnp.int32)
    assert abs(float(fused_linear_cross_entropy(h, hd, lb, chunk_size=3)) -
               float(masked_cross_entropy(h @ hd, lb))) < 1e-5
