"""Model-family tests: GPT, BERT/ERNIE, ViT — fwd shapes, grads, loss
descent, sharded compile on the virtual mesh (reference test model:
dygraph model-level parity tests + hybrid_strategy e2e configs)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.models import gpt, bert, vit


def _tree_finite(tree):
    return all(bool(jnp.all(jnp.isfinite(x))) for x in
               jax.tree_util.tree_leaves(tree))


# -- GPT --------------------------------------------------------------------
def test_gpt_forward_and_grad():
    cfg = gpt.GPTConfig(vocab_size=128, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, max_position_embeddings=32,
                        dtype=jnp.float32, remat=False)
    params = gpt.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    logits = gpt.forward(params, toks, cfg)
    assert logits.shape == (2, 16, 128)
    loss, grads = jax.value_and_grad(gpt.loss_fn)(params, toks[:, :-1],
                                                  toks[:, 1:], cfg)
    assert np.isfinite(float(loss)) and _tree_finite(grads)


def test_gpt_training_reduces_loss():
    cfg = gpt.GPTConfig(vocab_size=64, hidden_size=32,
                        intermediate_size=64, num_hidden_layers=1,
                        num_attention_heads=2, max_position_embeddings=16,
                        dtype=jnp.float32, remat=False)
    params = gpt.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    toks = jax.random.randint(jax.random.key(1), (4, 12), 0, 64)

    @jax.jit
    def step(params):
        loss, g = jax.value_and_grad(gpt.loss_fn)(params, toks[:, :-1],
                                                  toks[:, 1:], cfg)
        params = jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg,
                                        params, g)
        return params, loss

    losses = []
    for _ in range(20):
        params, loss = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7


# -- BERT / ERNIE -----------------------------------------------------------
def test_bert_forward_pooled_and_mlm():
    cfg = bert.BertConfig(vocab_size=128, hidden_size=64,
                          intermediate_size=128, num_hidden_layers=2,
                          num_attention_heads=4,
                          max_position_embeddings=32, dtype=jnp.float32,
                          remat=False)
    params = bert.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    seq, pooled = bert.forward(params, ids, cfg)
    assert seq.shape == (2, 16, 64) and pooled.shape == (2, 64)
    logits = bert.mlm_logits(params, seq, cfg)
    assert logits.shape == (2, 16, 128)
    # MLM loss with 15% masked labels
    labels = np.full((2, 16), -100, np.int64)
    labels[:, ::5] = np.asarray(ids)[:, ::5]
    loss, grads = jax.value_and_grad(bert.mlm_loss)(
        params, ids, jnp.asarray(labels), cfg)
    assert np.isfinite(float(loss)) and _tree_finite(grads)


def test_bert_attention_mask_zeroes_padding_influence():
    cfg = bert.BertConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=1,
                          num_attention_heads=2,
                          max_position_embeddings=16, dtype=jnp.float32,
                          remat=False)
    params = bert.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (1, 8), 0, 64)
    mask = jnp.array([[1, 1, 1, 1, 0, 0, 0, 0]])
    seq1, _ = bert.forward(params, ids, cfg, attention_mask=mask)
    # changing padded tokens must not change unpadded outputs
    ids2 = ids.at[0, 6].set((ids[0, 6] + 7) % 64)
    seq2, _ = bert.forward(params, ids2, cfg, attention_mask=mask)
    np.testing.assert_allclose(np.asarray(seq1[0, :4]),
                               np.asarray(seq2[0, :4]), rtol=1e-5,
                               atol=1e-5)
    assert bert.ErnieConfig is bert.BertConfig   # ERNIE alias


# -- ViT --------------------------------------------------------------------
def test_vit_forward_and_grad():
    cfg = vit.VIT_TINY
    cfg = vit.ViTConfig(**{**cfg.__dict__, "dtype": jnp.float32,
                           "remat": False})
    params = vit.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    imgs = jax.random.normal(jax.random.key(1), (2, 3, 32, 32))
    logits = vit.forward(params, imgs, cfg)
    assert logits.shape == (2, 10)
    labels = jnp.array([3, 7])
    loss, grads = jax.value_and_grad(vit.loss_fn)(params, imgs, labels, cfg)
    assert np.isfinite(float(loss)) and _tree_finite(grads)


# -- sharded compile on the virtual mesh ------------------------------------
@pytest.mark.parametrize("mod,make", [
    ("gpt", lambda: (gpt, gpt.GPTConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=32, dtype=jnp.float32, remat=False))),
    ("bert", lambda: (bert, bert.BertConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=32, dtype=jnp.float32, remat=False))),
])
def test_sharded_loss_compiles(mod, make):
    m, cfg = make()
    devices = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devices, ("fsdp", "tp"))
    params = m.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    specs = m.param_shardings(mesh, cfg)
    params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs)
    toks = jax.random.randint(jax.random.key(1), (4, 16), 0, 128)
    if mod == "gpt":
        loss = jax.jit(lambda p, a, b: m.loss_fn(p, a, b, cfg))(
            params, toks[:, :-1], toks[:, 1:])
    else:
        labels = jnp.where(toks % 5 == 0, toks, -100)
        loss = jax.jit(lambda p, a, b: m.mlm_loss(p, a, b, cfg))(
            params, toks, labels)
    assert np.isfinite(float(loss))
