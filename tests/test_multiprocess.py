"""Real multi-process distributed test through the production launcher
(reference: test/collective/test_communication_api_base.py:28,64 — shells
out to ``python -m paddle.distributed.launch``). Two processes on CPU,
rendezvoused via the launcher's TCPStore + the JAX coordination service,
exercising actual cross-process collectives (gloo transport) and a DP
train step whose gradients are averaged across ranks.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "collective_worker.py")
SUBGROUP_WORKER = os.path.join(REPO, "tests", "subgroup_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_collectives_through_launcher(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--log_dir", str(tmp_path / "log"), WORKER, str(tmp_path)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])

    results = []
    for r in range(2):
        f = tmp_path / f"rank_{r}.json"
        assert f.exists(), f"rank {r} wrote no results; launcher logs: " + \
            proc.stdout[-1000:]
        results.append(json.loads(f.read_text()))

    for r, res in enumerate(results):
        assert res["rank"] == r and res["world"] == 2
        # sum over ranks of (rank+1) = 3
        np.testing.assert_allclose(res["all_reduce"], [3.0] * 4)
        # gathered [rank0*10, rank1*10]
        np.testing.assert_allclose(res["all_gather"],
                                   [[0.0, 0.0], [10.0, 10.0]])
        # broadcast from rank 0: value 7
        np.testing.assert_allclose(res["broadcast"], [7.0] * 3)

    # DP step: both ranks end with IDENTICAL params (grad allreduce), and
    # rank-local losses differ (different data shards)
    p0, p1 = results[0]["params"], results[1]["params"]
    assert p0.keys() == p1.keys()
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], atol=1e-6)
    assert abs(results[0]["loss"] - results[1]["loss"]) > 1e-6


def test_subgroup_collectives_2_of_4(tmp_path):
    """Eager sub-group collectives in multi-process mode (VERDICT round-2
    #7): 2-of-4-rank groups must really communicate between exactly their
    member processes."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "4", "--master", f"127.0.0.1:{port}",
         "--log_dir", str(tmp_path / "log"), SUBGROUP_WORKER,
         str(tmp_path)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])

    results = {}
    for r in range(4):
        f = tmp_path / f"rank_{r}.json"
        assert f.exists(), f"rank {r} wrote no results; launcher logs: " + \
            proc.stdout[-1000:]
        results[r] = json.loads(f.read_text())

    for r in (1, 3):
        np.testing.assert_allclose(results[r]["sub_all_reduce"],
                                   [4.0, 4.0])           # 1 + 3
        np.testing.assert_allclose(results[r]["sub_broadcast"],
                                   [300.0, 300.0])       # from rank 3
        # reduce_scatter: sum [1+3]*4 = [4]*4, pos p keeps rows 2p:2p+2
        np.testing.assert_allclose(results[r]["sub_reduce_scatter"],
                                   [4.0, 4.0])
        # all_to_all: member p receives element p of each member's input
        pos = [1, 3].index(r)
        np.testing.assert_allclose(
            results[r]["sub_all_to_all"],
            [[0 * 10 + pos] * 2, [1 * 10 + pos] * 2])
    for r in (0, 2):
        np.testing.assert_allclose(results[r]["sub_all_gather"],
                                   [[5.0, 5.0], [7.0, 7.0]])
        np.testing.assert_allclose(results[r]["non_member"], [42.0, 42.0])
        # scatter from rank 2: member pos p gets [50+p]*2
        pos = [0, 2].index(r)
        np.testing.assert_allclose(results[r]["sub_scatter"],
                                   [50.0 + pos] * 2)
    for r in range(4):
        np.testing.assert_allclose(results[r]["world_all_reduce"],
                                   [4.0, 4.0])
