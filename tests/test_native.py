"""Native C++ runtime tests: TCPStore server (csrc/tcp_store.cc), shm ring
queue (csrc/shm_queue.cc), multiprocess DataLoader.

Reference test model: C++ store gtests + test/custom_runtime fake-device
multi-process fixtures (SURVEY §4)."""
import multiprocessing
import os
import pickle
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core.native import (load_native, SharedMemoryQueue,
                                    native_store_server, native_store_stop)
from paddle_tpu.distributed.store import TCPStore, TCPStoreServer

native_available = load_native() is not None
needs_native = pytest.mark.skipif(not native_available,
                                  reason="native lib unavailable")


@needs_native
def test_native_store_full_protocol():
    srv = TCPStoreServer()
    assert srv.backend == "native"
    c = TCPStore("127.0.0.1", srv.port)
    c.set("k", "v")
    assert c.get("k") == b"v"
    assert c.get("nope") is None
    assert c.add("n", 4) == 4
    assert c.add("n", -1) == 3
    c.delete("k")
    assert c.get("k") is None
    c.set("pre/a", "1")
    c.set("pre/b", "2")
    assert sorted(c.list_keys("pre/")) == ["pre/a", "pre/b"]
    with pytest.raises(TimeoutError):
        c.wait("never", timeout=0.3)
    c.close()
    srv.close()


@needs_native
def test_native_store_parked_waiters_and_barrier():
    srv = TCPStoreServer(backend="native")
    results = []

    def waiter():
        c = TCPStore("127.0.0.1", srv.port)
        c.wait("flag", timeout=15.0)
        c.barrier("b", 3, timeout=15.0)
        results.append(1)
        c.close()

    threads = [threading.Thread(target=waiter) for _ in range(2)]
    for t in threads:
        t.start()
    main = TCPStore("127.0.0.1", srv.port)
    time.sleep(0.3)
    main.set("flag", "go")
    main.barrier("b", 3, timeout=15.0)
    for t in threads:
        t.join(timeout=15)
    assert results == [1, 1]
    main.close()
    srv.close()


def test_store_python_fallback():
    srv = TCPStoreServer(backend="python")
    assert srv.backend == "python"
    c = TCPStore("127.0.0.1", srv.port)
    c.set("x", "y")
    assert c.get("x") == b"y"
    c.close()
    srv.close()


@needs_native
def test_shm_queue_roundtrip_and_wrap():
    q = SharedMemoryQueue("/ptq_t1", capacity=1 << 16)
    try:
        # many messages larger than capacity in aggregate → exercises wrap
        for i in range(100):
            msg = bytes([i % 256]) * (300 + 17 * (i % 13))
            q.put(msg)
            out = q.get()
            assert out == msg
        # queue several then drain
        msgs = [os.urandom(1000) for _ in range(20)]
        for m in msgs:
            q.put(m)
        assert q.qsize() == 20
        assert [q.get() for _ in range(20)] == msgs
    finally:
        q.close()


@needs_native
def test_shm_queue_blocking_timeout():
    q = SharedMemoryQueue("/ptq_t2", capacity=1 << 12)
    try:
        with pytest.raises(TimeoutError):
            q.get(timeout=0.2)
        big = b"z" * 3000
        q.put(big)
        with pytest.raises(TimeoutError):   # full: 2nd big won't fit
            q.put(big, timeout=0.2)
        assert q.get() == big
    finally:
        q.close()


def _producer(name, n):
    q = SharedMemoryQueue(name, create=False)
    for i in range(n):
        q.put(pickle.dumps((os.getpid(), i)))


@needs_native
def test_shm_queue_cross_process():
    q = SharedMemoryQueue("/ptq_t3", capacity=1 << 20)
    try:
        ctx = multiprocessing.get_context("fork")
        procs = [ctx.Process(target=_producer, args=("/ptq_t3", 50))
                 for _ in range(3)]
        for p in procs:
            p.start()
        got = [pickle.loads(q.get(timeout=30)) for _ in range(150)]
        for p in procs:
            p.join(timeout=10)
        per_pid = {}
        for pid, i in got:
            per_pid.setdefault(pid, []).append(i)
        assert len(per_pid) == 3
        for seq in per_pid.values():   # per-producer FIFO order preserved
            assert seq == sorted(seq)
    finally:
        q.close()


class _SquareDataset:
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.full((4,), i, np.float32), np.int64(i)


@pytest.mark.slow
def test_dataloader_process_workers():
    from paddle_tpu.io import DataLoader
    ds = _SquareDataset(32)
    loader = DataLoader(ds, batch_size=4, num_workers=2,
                        worker_type="process", prefetch_to_device=False)
    seen = []
    for x, y in loader:
        assert tuple(x.shape) == (4, 4)
        seen.extend(int(v) for v in np.asarray(y._value
                                               if hasattr(y, "_value")
                                               else y))
    assert sorted(seen) == list(range(32))


def test_dataloader_worker_death_detected():
    from paddle_tpu.io import DataLoader

    class Killer(_SquareDataset):
        def __getitem__(self, i):
            if i == 5:
                os._exit(9)   # simulate OOM-kill, no exception raised
            return super().__getitem__(i)

    loader = DataLoader(Killer(16), batch_size=4, num_workers=1,
                        worker_type="process", prefetch_to_device=False)
    with pytest.raises(RuntimeError, match="exited unexpectedly"):
        for _ in loader:
            pass


@pytest.mark.slow
def test_dataloader_user_timeout_honored():
    from paddle_tpu.io import DataLoader

    class Slow(_SquareDataset):
        def __getitem__(self, i):
            if i >= 4:
                time.sleep(30)
            return super().__getitem__(i)

    loader = DataLoader(Slow(16), batch_size=4, num_workers=1,
                        worker_type="process", prefetch_to_device=False,
                        timeout=6)
    with pytest.raises(TimeoutError, match="workers alive"):
        for _ in loader:
            pass


def test_dataloader_process_worker_error_propagates():
    from paddle_tpu.io import DataLoader

    class Bad(_SquareDataset):
        def __getitem__(self, i):
            if i == 5:
                raise ValueError("boom")
            return super().__getitem__(i)

    loader = DataLoader(Bad(16), batch_size=4, num_workers=2,
                        worker_type="process", prefetch_to_device=False)
    with pytest.raises(RuntimeError, match="boom"):
        for _ in loader:
            pass
