"""Device-side (jit-able) NMS family vs the host reference
implementations (reference: phi/kernels/gpu/nms_kernel.cu,
ops.yaml multiclass_nms3 / matrix_nms)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.vision import ops as vops
from paddle_tpu.vision.nms_device import (matrix_nms_padded,
                                          multiclass_nms_padded, nms_padded)

def _rand_boxes(m, scale=40.0, seed=0):
    r = np.random.RandomState(seed)
    xy = r.rand(m, 2) * scale
    wh = r.rand(m, 2) * 12 + 0.5
    return np.concatenate([xy, xy + wh], axis=1).astype(np.float32)


class TestNmsPadded:
    def test_matches_host_nms(self):
        b = _rand_boxes(64, seed=7)
        s = np.random.RandomState(8).rand(64).astype(np.float32)
        keep_host = np.asarray(vops.nms(b, iou_threshold=0.4,
                                        scores=s).numpy())
        keep_dev, num = nms_padded(jnp.asarray(b), jnp.asarray(s),
                                   iou_threshold=0.4, max_out=64)
        keep_dev = np.asarray(keep_dev)[:int(num)]
        np.testing.assert_array_equal(keep_dev, keep_host)

    def test_categories_suppress_within_class_only(self):
        b = _rand_boxes(48, seed=9)
        s = np.random.RandomState(10).rand(48).astype(np.float32)
        cat = np.random.RandomState(11).randint(0, 3, 48)
        keep_host = np.asarray(vops.nms(b, iou_threshold=0.3, scores=s,
                                        category_idxs=cat).numpy())
        keep_dev, num = nms_padded(jnp.asarray(b), jnp.asarray(s),
                                   iou_threshold=0.3,
                                   category_idxs=jnp.asarray(cat),
                                   max_out=48)
        np.testing.assert_array_equal(np.asarray(keep_dev)[:int(num)],
                                      keep_host)

    def test_top_k_and_padding(self):
        b = _rand_boxes(32, seed=12)
        s = np.random.RandomState(13).rand(32).astype(np.float32)
        keep, num = nms_padded(jnp.asarray(b), jnp.asarray(s),
                               iou_threshold=0.99, max_out=8)
        assert keep.shape == (8,)
        # iou 0.99 keeps nearly everything -> survivors overflow max_out;
        # num is clamped to the slots actually returned
        assert int(num) == 8
        assert (np.asarray(keep) >= 0).all()

    def test_pre_top_k_bounds_candidates(self):
        b = _rand_boxes(64, seed=17)
        s = np.random.RandomState(18).rand(64).astype(np.float32)
        # pre_top_k == M is exact; smaller pre_top_k considers only the
        # top-scored candidates (host analogue: nms_top_k pre-selection)
        full, n_full = nms_padded(jnp.asarray(b), jnp.asarray(s),
                                  iou_threshold=0.4, max_out=64,
                                  pre_top_k=64)
        capped, n_cap = nms_padded(jnp.asarray(b), jnp.asarray(s),
                                   iou_threshold=0.4, max_out=64,
                                   pre_top_k=16)
        assert int(n_cap) <= 16
        kept_full = set(np.asarray(full)[:int(n_full)].tolist())
        kept_cap = np.asarray(capped)[:int(n_cap)].tolist()
        top16 = set(np.argsort(-s)[:16].tolist())
        assert set(kept_cap) <= top16
        # candidates surviving in the capped run also survive the full run
        assert set(kept_cap) <= kept_full

    def test_score_threshold(self):
        b = _rand_boxes(16, seed=14)
        s = np.linspace(0, 1, 16).astype(np.float32)
        keep, num = nms_padded(jnp.asarray(b), jnp.asarray(s),
                               iou_threshold=1.0, score_threshold=0.5,
                               max_out=16)
        kept = np.asarray(keep)[:int(num)]
        assert (s[kept] > 0.5).all()

    def test_works_under_outer_jit(self):
        b = jnp.asarray(_rand_boxes(16, seed=15))
        s = jnp.asarray(np.random.RandomState(16).rand(16), jnp.float32)

        @jax.jit
        def f(b, s):
            keep, num = nms_padded(b, s, iou_threshold=0.4, max_out=16)
            return keep, num

        keep, num = f(b, s)
        assert int(num) > 0


def _mc_host_as_sets(out, nums, index):
    """(cls, score, idx) tuples per image from the host return."""
    out = np.asarray(out.numpy()).reshape(-1, 6)
    nums = np.asarray(nums.numpy())
    index = np.asarray(index.numpy())
    res, p = [], 0
    for n in nums:
        rows = out[p:p + n]
        idx = index[p:p + n]
        res.append(sorted((int(r[0]), round(float(r[1]), 5), int(i))
                          for r, i in zip(rows, idx)))
        p += n
    return res


def _mc_dev_as_sets(out, nums, index):
    out, nums, index = map(np.asarray, (out, nums, index))
    res = []
    for b in range(out.shape[0]):
        n = int(nums[b])
        res.append(sorted((int(out[b, i, 0]), round(float(out[b, i, 1]), 5),
                           int(index[b, i])) for i in range(n)))
    return res


class TestMulticlassNmsPadded:
    def _data(self, B=2, M=40, C=4, seed=21):
        r = np.random.RandomState(seed)
        bb = np.stack([_rand_boxes(M, seed=seed + i) for i in range(B)])
        sc = r.rand(B, C, M).astype(np.float32)
        return bb, sc

    def test_matches_host(self):
        bb, sc = self._data()
        host = vops.multiclass_nms(bb, sc, score_threshold=0.3,
                                   nms_top_k=20, keep_top_k=12,
                                   nms_threshold=0.45, return_index=True)
        dev = multiclass_nms_padded(jnp.asarray(bb), jnp.asarray(sc),
                                    score_threshold=0.3, nms_top_k=20,
                                    keep_top_k=12, nms_threshold=0.45)
        assert _mc_host_as_sets(host[0], host[1], host[2]) == \
            _mc_dev_as_sets(dev[0], dev[2], dev[1])

    def test_adaptive_eta_matches_host(self):
        bb, sc = self._data(seed=31)
        host = vops.multiclass_nms(bb, sc, score_threshold=0.2,
                                   nms_top_k=30, keep_top_k=16,
                                   nms_threshold=0.7, nms_eta=0.9,
                                   return_index=True)
        dev = multiclass_nms_padded(jnp.asarray(bb), jnp.asarray(sc),
                                    score_threshold=0.2, nms_top_k=30,
                                    keep_top_k=16, nms_threshold=0.7,
                                    nms_eta=0.9)
        assert _mc_host_as_sets(host[0], host[1], host[2]) == \
            _mc_dev_as_sets(dev[0], dev[2], dev[1])

    def test_background_label_excluded(self):
        bb, sc = self._data(seed=41)
        sc[:, 0, :] = 0.99  # background class would dominate
        dev = multiclass_nms_padded(jnp.asarray(bb), jnp.asarray(sc),
                                    score_threshold=0.3, keep_top_k=10,
                                    background_label=0)
        out, nums = np.asarray(dev[0]), np.asarray(dev[2])
        for b in range(out.shape[0]):
            assert (out[b, :nums[b], 0] != 0).all()

    def test_no_candidates_gives_zero(self):
        bb, sc = self._data(seed=51)
        dev = multiclass_nms_padded(jnp.asarray(bb), jnp.asarray(sc),
                                    score_threshold=2.0, keep_top_k=10)
        assert (np.asarray(dev[2]) == 0).all()
        assert (np.asarray(dev[0]) == 0).all()
        assert (np.asarray(dev[1]) == -1).all()


class TestMatrixNmsPadded:
    def _data(self, B=2, M=32, C=3, seed=61):
        r = np.random.RandomState(seed)
        bb = np.stack([_rand_boxes(M, seed=seed + i) for i in range(B)])
        sc = r.rand(B, C, M).astype(np.float32)
        return bb, sc

    @pytest.mark.parametrize("gauss", [False, True])
    def test_matches_host(self, gauss):
        bb, sc = self._data(seed=61 + int(gauss))
        host = vops.matrix_nms(bb, sc, score_threshold=0.4,
                               post_threshold=0.2, nms_top_k=20,
                               keep_top_k=10, use_gaussian=gauss,
                               return_index=True)
        dev = matrix_nms_padded(jnp.asarray(bb), jnp.asarray(sc),
                                score_threshold=0.4, post_threshold=0.2,
                                nms_top_k=20, keep_top_k=10,
                                use_gaussian=gauss)
        assert _mc_host_as_sets(host[0], host[1], host[2]) == \
            _mc_dev_as_sets(dev[0], dev[2], dev[1])


class TestGenerateProposalsPadded:
    def _data(self, N=2, A=3, H=5, W=4, seed=71):
        r = np.random.RandomState(seed)
        sc = r.rand(N, A, H, W).astype(np.float32)
        bd = (r.randn(N, 4 * A, H, W) * 0.3).astype(np.float32)
        ims = np.array([[48.0, 40.0]] * N, np.float32)
        # grid anchors of varying size: top-left at (x*8, y*8)
        anc = np.zeros((H, W, A, 4), np.float32)
        xs = np.tile(np.arange(W)[None, :] * 8.0, (H, 1))
        ys = np.tile(np.arange(H)[:, None] * 8.0, (1, W))
        for a in range(A):
            s = 6.0 + 4 * a
            anc[..., a, 0] = xs
            anc[..., a, 1] = ys
            anc[..., a, 2] = xs + s
            anc[..., a, 3] = ys + s
        var = np.full((H, W, A, 4), 0.5, np.float32)
        return sc, bd, ims, anc, var

    @pytest.mark.parametrize("pixel_offset", [False, True])
    def test_matches_host(self, pixel_offset):
        from paddle_tpu.vision.nms_device import generate_proposals_padded
        sc, bd, ims, anc, var = self._data(seed=71 + int(pixel_offset))
        host_rois, host_probs, host_num = vops.generate_proposals(
            sc, bd, ims, anc, var, pre_nms_top_n=40, post_nms_top_n=12,
            nms_thresh=0.5, min_size=2.0, pixel_offset=pixel_offset,
            return_rois_num=True)
        rois, probs, nums = generate_proposals_padded(
            jnp.asarray(sc), jnp.asarray(bd), jnp.asarray(ims),
            jnp.asarray(anc), jnp.asarray(var), pre_nms_top_n=40,
            post_nms_top_n=12, nms_thresh=0.5, min_size=2.0,
            pixel_offset=pixel_offset)
        hr = np.asarray(host_rois.numpy())
        hp = np.asarray(host_probs.numpy())
        hn = np.asarray(host_num.numpy())
        np.testing.assert_array_equal(np.asarray(nums), hn)
        ofs = 0
        for i in range(sc.shape[0]):
            ni = int(hn[i])
            np.testing.assert_allclose(
                np.asarray(rois)[i, :ni], hr[ofs:ofs + ni],
                rtol=1e-4, atol=1e-4, err_msg=f"img {i}")
            np.testing.assert_allclose(
                np.asarray(probs)[i, :ni, 0], hp[ofs:ofs + ni, 0],
                rtol=1e-5, err_msg=f"img {i}")
            assert (np.asarray(rois)[i, ni:] == 0).all()
            ofs += ni

    def test_static_shape_with_few_candidates(self):
        """post_nms_top_n larger than the candidate pool must still
        return the advertised [N, post_nms_top_n, 4] shape (zero pad)."""
        from paddle_tpu.vision.nms_device import generate_proposals_padded
        sc, bd, ims, anc, var = self._data(seed=81)
        k_total = sc.shape[1] * sc.shape[2] * sc.shape[3]
        # raw numpy inputs must work too (converted internally)
        rois, probs, nums = generate_proposals_padded(
            sc, bd, ims, anc, var,
            pre_nms_top_n=-1, post_nms_top_n=k_total + 50, min_size=2.0)
        assert rois.shape == (2, k_total + 50, 4)
        assert probs.shape == (2, k_total + 50, 1)
        assert (np.asarray(rois)[0, int(nums[0]):] == 0).all()

    def test_jits_as_one_program(self):
        from paddle_tpu.vision.nms_device import generate_proposals_padded
        sc, bd, ims, anc, var = self._data(seed=91)
        f = jax.jit(lambda s, d, im: generate_proposals_padded(
            s, d, im, jnp.asarray(anc), jnp.asarray(var),
            pre_nms_top_n=30, post_nms_top_n=8, min_size=2.0))
        rois, probs, nums = f(jnp.asarray(sc), jnp.asarray(bd),
                              jnp.asarray(ims))
        assert rois.shape == (2, 8, 4) and nums.shape == (2,)
        assert int(nums.sum()) > 0
