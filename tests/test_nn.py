"""nn layer tests (reference analog: test/legacy_test/test_layers.py etc.)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestLayerBase:
    def test_parameter_registry(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(F.relu(self.fc1(x)))

        net = Net()
        names = [n for n, _ in net.named_parameters()]
        assert "fc1.weight" in names and "fc2.bias" in names
        assert len(net.parameters()) == 4
        assert len(net.sublayers()) == 2

    def test_state_dict_roundtrip(self):
        net = nn.Sequential(nn.Linear(3, 5), nn.ReLU(), nn.Linear(5, 2))
        sd = net.state_dict()
        net2 = nn.Sequential(nn.Linear(3, 5), nn.ReLU(), nn.Linear(5, 2))
        missing, unexpected = net2.set_state_dict(sd)
        assert not missing and not unexpected
        x = paddle.randn([2, 3])
        np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(),
                                   rtol=1e-6)

    def test_save_load(self, tmp_path):
        net = nn.Linear(3, 2)
        path = str(tmp_path / "model.pdparams")
        paddle.save(net.state_dict(), path)
        loaded = paddle.load(path)
        net2 = nn.Linear(3, 2)
        net2.set_state_dict(loaded)
        np.testing.assert_allclose(net.weight.numpy(), net2.weight.numpy())

    def test_train_eval_mode(self):
        d = nn.Dropout(0.5)
        x = paddle.ones([100, 100])
        d.eval()
        np.testing.assert_allclose(d(x).numpy(), x.numpy())
        d.train()
        out = d(x)
        assert (out.numpy() == 0).any()

    def test_hooks(self):
        lin = nn.Linear(2, 2)
        calls = []
        h = lin.register_forward_post_hook(
            lambda l, i, o: calls.append(1))
        lin(paddle.randn([1, 2]))
        assert calls == [1]
        h.remove()
        lin(paddle.randn([1, 2]))
        assert calls == [1]

    def test_to_dtype(self):
        lin = nn.Linear(2, 2)
        lin.to(dtype="bfloat16")
        assert lin.weight.dtype == paddle.bfloat16


class TestCoreLayers:
    def test_linear_numeric(self):
        lin = nn.Linear(3, 4)
        x = np.random.randn(5, 3).astype(np.float32)
        want = x @ lin.weight.numpy() + lin.bias.numpy()
        np.testing.assert_allclose(lin(paddle.to_tensor(x)).numpy(), want,
                                   rtol=1e-5, atol=1e-5)

    def test_conv2d_vs_naive(self):
        conv = nn.Conv2D(2, 3, 3, padding=1)
        x = np.random.randn(1, 2, 5, 5).astype(np.float32)
        out = conv(paddle.to_tensor(x))
        assert out.shape == [1, 3, 5, 5]
        # check against explicit correlation
        w = conv.weight.numpy()
        b = conv.bias.numpy()
        xp = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)])
        want = np.zeros((1, 3, 5, 5), np.float32)
        for oc in range(3):
            for i in range(5):
                for j in range(5):
                    want[0, oc, i, j] = np.sum(
                        xp[0, :, i:i + 3, j:j + 3] * w[oc]) + b[oc]
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-4, atol=1e-4)

    @pytest.mark.slow
    def test_conv2d_stride_groups(self):
        conv = nn.Conv2D(4, 8, 3, stride=2, padding=1, groups=2)
        out = conv(paddle.randn([2, 4, 8, 8]))
        assert out.shape == [2, 8, 4, 4]

    @pytest.mark.slow
    def test_conv_transpose(self):
        conv = nn.Conv2DTranspose(3, 5, 4, stride=2, padding=1)
        out = conv(paddle.randn([1, 3, 8, 8]))
        assert out.shape == [1, 5, 16, 16]

    @pytest.mark.slow
    def test_batchnorm_stats(self):
        bn = nn.BatchNorm2D(3, momentum=0.9)
        x = paddle.randn([4, 3, 8, 8]) * 2 + 1
        bn.train()
        out = bn(x)
        m = out.numpy().mean(axis=(0, 2, 3))
        np.testing.assert_allclose(m, np.zeros(3), atol=1e-5)
        assert not np.allclose(bn._mean.numpy(), np.zeros(3))
        bn.eval()
        out2 = bn(x)
        assert out2.shape == [4, 3, 8, 8]

    def test_layernorm(self):
        ln = nn.LayerNorm(16)
        x = paddle.randn([2, 4, 16]) * 3 + 5
        out = ln(x).numpy()
        np.testing.assert_allclose(out.mean(-1), np.zeros((2, 4)),
                                   atol=1e-4)
        np.testing.assert_allclose(out.std(-1), np.ones((2, 4)), atol=1e-2)

    def test_rmsnorm(self):
        rn = nn.RMSNorm(8)
        x = paddle.randn([2, 8])
        out = rn(x).numpy()
        xf = x.numpy()
        want = xf / np.sqrt((xf ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-4)

    @pytest.mark.slow
    def test_groupnorm_embedding(self):
        gn = nn.GroupNorm(2, 4)
        assert gn(paddle.randn([2, 4, 3, 3])).shape == [2, 4, 3, 3]
        emb = nn.Embedding(10, 6, padding_idx=0)
        out = emb(paddle.to_tensor([[1, 0, 3]]))
        assert out.shape == [1, 3, 6]
        assert np.allclose(out.numpy()[0, 1], 0)

    def test_pools(self):
        x = paddle.to_tensor(np.arange(16, dtype=np.float32
                                       ).reshape(1, 1, 4, 4))
        mp = nn.MaxPool2D(2, 2)(x)
        np.testing.assert_allclose(mp.numpy()[0, 0], [[5, 7], [13, 15]])
        ap = nn.AvgPool2D(2, 2)(x)
        np.testing.assert_allclose(ap.numpy()[0, 0], [[2.5, 4.5],
                                                      [10.5, 12.5]])
        aap = nn.AdaptiveAvgPool2D(1)(x)
        np.testing.assert_allclose(aap.numpy()[0, 0, 0, 0], 7.5)

    def test_activations(self):
        x = paddle.to_tensor([-1.0, 0.0, 2.0])
        np.testing.assert_allclose(nn.ReLU()(x).numpy(), [0, 0, 2])
        assert nn.GELU()(x).shape == [3]
        out = F.softmax(x)
        np.testing.assert_allclose(out.numpy().sum(), 1.0, rtol=1e-6)

    @pytest.mark.slow
    def test_rnn_lstm_gru(self):
        for cls, states in [(nn.SimpleRNN, 1), (nn.LSTM, 2), (nn.GRU, 1)]:
            m = cls(4, 8, num_layers=2)
            out, st = m(paddle.randn([3, 5, 4]))
            assert out.shape == [3, 5, 8]
            if states == 2:
                assert st[0].shape == [2, 3, 8]
            else:
                assert st.shape == [2, 3, 8]

    @pytest.mark.slow
    def test_bidirectional_lstm(self):
        m = nn.LSTM(4, 8, direction="bidirect")
        out, (h, c) = m(paddle.randn([2, 5, 4]))
        assert out.shape == [2, 5, 16]
        assert h.shape == [2, 2, 8]

    def test_multihead_attention(self):
        mha = nn.MultiHeadAttention(16, 4)
        q = paddle.randn([2, 6, 16])
        out = mha(q, q, q)
        assert out.shape == [2, 6, 16]

    def test_transformer_encoder(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        out = enc(paddle.randn([2, 6, 16]))
        assert out.shape == [2, 6, 16]


class TestLosses:
    def test_cross_entropy(self):
        logits = np.random.randn(4, 10).astype(np.float32)
        labels = np.array([1, 3, 5, 9])
        loss = F.cross_entropy(paddle.to_tensor(logits),
                               paddle.to_tensor(labels))
        # numpy reference
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        want = -np.log(p[np.arange(4), labels]).mean()
        np.testing.assert_allclose(float(loss.item()), want, rtol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = np.random.randn(4, 5).astype(np.float32)
        labels = np.array([1, -100, 2, -100])
        loss = F.cross_entropy(paddle.to_tensor(logits),
                               paddle.to_tensor(labels), ignore_index=-100)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        want = -np.log(p[[0, 2], [1, 2]]).mean()
        np.testing.assert_allclose(float(loss.item()), want, rtol=1e-5)

    def test_soft_label_and_smoothing(self):
        logits = paddle.randn([3, 6])
        soft = F.softmax(paddle.randn([3, 6]))
        loss = F.cross_entropy(logits, soft, soft_label=True)
        assert loss.size == 1
        loss2 = F.cross_entropy(logits, paddle.to_tensor([0, 1, 2]),
                                label_smoothing=0.1)
        assert loss2.size == 1

    def test_mse_l1_bce(self):
        a = paddle.randn([4, 3])
        b = paddle.randn([4, 3])
        np.testing.assert_allclose(
            float(F.mse_loss(a, b).item()),
            ((a.numpy() - b.numpy()) ** 2).mean(), rtol=1e-5)
        p = paddle.nn.functional.sigmoid(a)
        y = paddle.to_tensor((np.random.rand(4, 3) > 0.5
                              ).astype(np.float32))
        l1 = F.binary_cross_entropy(p, y)
        l2 = F.binary_cross_entropy_with_logits(a, y)
        np.testing.assert_allclose(float(l1.item()), float(l2.item()),
                                   rtol=1e-4)

    def test_kl_smooth_l1(self):
        logp = F.log_softmax(paddle.randn([3, 5]))
        q = F.softmax(paddle.randn([3, 5]))
        assert F.kl_div(logp, q).size == 1
        assert F.smooth_l1_loss(paddle.randn([3]), paddle.randn([3])).size == 1

    @pytest.mark.slow
    def test_ctc_loss_runs(self):
        T, B, C, S = 12, 2, 6, 4
        logits = paddle.randn([T, B, C])
        labels = paddle.to_tensor(
            np.random.randint(1, C, (B, S)).astype(np.int32))
        loss = F.ctc_loss(logits, labels,
                          paddle.to_tensor(np.full(B, T, np.int64)),
                          paddle.to_tensor(np.full(B, S, np.int64)))
        assert np.isfinite(float(loss.item()))


class TestGradFlow:
    @pytest.mark.slow
    def test_mlp_training_reduces_loss(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 1))
        opt = paddle.optimizer.Adam(0.01, parameters=net.parameters())
        x = paddle.randn([64, 8])
        w = paddle.randn([8, 1])
        y = paddle.matmul(x, w)
        losses = []
        for _ in range(60):
            pred = net(x)
            loss = F.mse_loss(pred, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.item()))
        assert losses[-1] < losses[0] * 0.15, losses[::10]

    @pytest.mark.slow
    def test_conv_bn_backward(self):
        net = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.BatchNorm2D(8),
                            nn.ReLU(), nn.Conv2D(8, 4, 1))
        out = net(paddle.randn([2, 3, 8, 8]))
        out.mean().backward()
        for p in net.parameters():
            assert p.grad is not None, p.name

    def test_weight_decay_and_clip(self):
        lin = nn.Linear(4, 4)
        clip = nn.ClipGradByGlobalNorm(0.1)
        opt = paddle.optimizer.Momentum(0.1, parameters=lin.parameters(),
                                        weight_decay=0.01, grad_clip=clip)
        (lin(paddle.randn([8, 4])) ** 2).sum().backward()
        opt.step()


class TestOptimizers:
    @pytest.mark.parametrize("cls,kw", [
        ("SGD", {}), ("Momentum", {}), ("Adam", {}), ("AdamW", {}),
        ("Adagrad", {"learning_rate": 0.01}),
        ("RMSProp", {"learning_rate": 0.01}),
        ("Adamax", {}), ("Adadelta", {}), ("Lamb", {}), ("NAdam", {}),
        ("RAdam", {}),
    ])
    def test_step_changes_params(self, cls, kw):
        lin = nn.Linear(3, 3)
        kw.setdefault("learning_rate", 0.05)
        opt = getattr(paddle.optimizer, cls)(parameters=lin.parameters(),
                                             **kw)
        (lin(paddle.randn([4, 3])) ** 2).sum().backward()
        w0 = lin.weight.numpy().copy()
        opt.step()
        assert not np.allclose(w0, lin.weight.numpy())

    def test_adam_matches_reference_formula(self):
        p0 = np.array([1.0, -2.0], np.float32)
        g = np.array([0.5, 0.25], np.float32)
        lin_p = paddle.framework.Parameter(p0.copy())
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[lin_p],
                                    multi_precision=False)
        lin_p.grad = paddle.to_tensor(g)
        opt.step()
        m = 0.1 * g
        v = 0.001 * g * g
        mhat = m / (1 - 0.9)
        vhat = v / (1 - 0.999)
        want = p0 - 0.1 * mhat / (np.sqrt(vhat) + 1e-8)
        np.testing.assert_allclose(lin_p.numpy(), want, rtol=1e-5)

    def test_lr_scheduler(self):
        lin = nn.Linear(2, 2)
        sched = paddle.optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        opt = paddle.optimizer.SGD(sched, parameters=lin.parameters())
        assert abs(opt.get_lr() - 0.1) < 1e-9
        sched.step()
        sched.step()
        assert abs(opt.get_lr() - 0.05) < 1e-9

    def test_warmup(self):
        s = paddle.optimizer.lr.LinearWarmup(0.1, 10, 0.0, 0.1)
        vals = []
        for _ in range(12):
            vals.append(s())
            s.step()
        assert vals[0] == 0.0 and abs(vals[5] - 0.05) < 1e-9
        assert abs(vals[11] - 0.1) < 1e-9

    def test_optimizer_state_roundtrip(self):
        lin = nn.Linear(2, 2)
        opt = paddle.optimizer.Adam(parameters=lin.parameters())
        (lin(paddle.randn([2, 2]))).sum().backward()
        opt.step()
        state = opt.state_dict()
        opt2 = paddle.optimizer.Adam(parameters=lin.parameters())
        opt2.set_state_dict(state)
        assert opt2._global_step == opt._global_step


class TestAMP:
    def test_autocast_casts_matmul(self):
        a = paddle.randn([4, 4])
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            out = paddle.matmul(a, a)
            assert out.dtype == paddle.bfloat16
            s = paddle.nn.functional.softmax(out)
            assert s.dtype == np.float32  # black list promotes
        out2 = paddle.matmul(a, a)
        assert out2.dtype == np.float32

    def test_grad_scaler(self):
        lin = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
        loss = (lin(paddle.randn([2, 4])) ** 2).mean()
        scaler.scale(loss).backward()
        scaler.step(opt)
        scaler.update()
        g = lin.weight.grad
        assert g is not None

    def test_scaler_skips_on_inf(self):
        lin = nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=64.0)
        lin.weight.grad = paddle.to_tensor(
            np.full((2, 2), np.inf, np.float32))
        lin.bias.grad = paddle.to_tensor(np.zeros(2, np.float32))
        w0 = lin.weight.numpy().copy()
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(lin.weight.numpy(), w0)
        assert scaler.get_loss_scaling() == 32.0


class TestDataLoader:
    def test_basic_iteration(self):
        from paddle_tpu.io import DataLoader, TensorDataset
        x = paddle.randn([20, 3])
        y = paddle.arange(20)
        ds = TensorDataset([x, y])
        loader = DataLoader(ds, batch_size=6, drop_last=False)
        batches = list(loader)
        assert len(batches) == 4
        assert batches[0][0].shape == [6, 3]
        assert batches[-1][0].shape == [2, 3]

    def test_shuffle_and_workers(self):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.vision.datasets import FakeData
        ds = FakeData(size=32, image_shape=(3, 8, 8), num_classes=4)
        loader = DataLoader(ds, batch_size=8, shuffle=True, num_workers=2)
        seen = 0
        for img, lab in loader:
            assert img.shape == [8, 3, 8, 8]
            seen += 8
        assert seen == 32

    def test_device_prefetch_order_and_type(self):
        # num_workers=0 now routes through _DevicePrefetchIter: batches
        # must arrive in order, on device, with no duplicates or drops
        from paddle_tpu.io import DataLoader
        from paddle_tpu.io.dataloader import _DevicePrefetchIter

        class DS:
            def __len__(self):
                return 24

            def __getitem__(self, i):
                return np.full((3,), i, np.float32), np.int64(i)

        loader = DataLoader(DS(), batch_size=4)
        it = iter(loader)
        assert isinstance(it, _DevicePrefetchIter)
        labels = []
        for xb, yb in it:
            assert xb.shape == [4, 3]
            labels.extend(int(v) for v in yb.numpy())
        assert labels == list(range(24))

    def test_device_prefetch_propagates_worker_error(self):
        from paddle_tpu.io import DataLoader

        class Bad:
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("poison sample")
                return np.zeros((2,), np.float32)

        loader = DataLoader(Bad(), batch_size=2)
        with pytest.raises(ValueError, match="poison sample"):
            list(loader)

    def test_device_prefetch_overlaps_stage_with_consumer(self):
        # steady state must approach max(stage, consume), not their sum
        import time as _t
        from paddle_tpu.io.dataloader import _DevicePrefetchIter

        def stage(b):
            _t.sleep(0.05)
            return b

        pf = _DevicePrefetchIter(iter(range(8)), stage, depth=2)
        assert next(pf) == 0  # first item pays its own stage latency
        t0 = _t.perf_counter()
        out = []
        for item in pf:
            _t.sleep(0.05)  # "compute" — stage of next item runs under it
            out.append(item)
        dt = _t.perf_counter() - t0
        assert out == list(range(1, 8))
        # serial would be 7*(0.05+0.05)=0.70s; overlapped ~0.35s
        assert dt < 0.55, f"no overlap: {dt:.3f}s"

    def test_trainer_prefetch_stages_batches(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.trainer import (MeshConfig, Trainer,
                                                    make_mesh)

        mesh = make_mesh(MeshConfig())

        def loss_fn(p, x, y):
            pred = x @ p["w"]
            return ((pred - y) ** 2).mean()

        params = {"w": jnp.ones((4, 4), jnp.float32)}
        tr = Trainer(loss_fn, mesh, {"w": jax.sharding.PartitionSpec()},
                     lr=1e-2)
        state = tr.init_state(params)
        xb0 = np.random.randn(8, 4).astype(np.float32)
        yb0 = np.random.randn(8, 4).astype(np.float32)
        host = [(xb0, yb0)] * 3  # fixed batch → loss must descend
        losses = []
        for xb, yb in tr.prefetch(iter(host)):
            assert isinstance(xb, jax.Array)
            state, m = tr.step(state, xb, yb)
            losses.append(float(m["loss"]))
        assert len(losses) == 3
        assert losses[-1] < losses[0]

    def test_distributed_sampler_shards(self):
        from paddle_tpu.io import DistributedBatchSampler
        from paddle_tpu.vision.datasets import FakeData
        ds = FakeData(size=20, image_shape=(1,), num_classes=2)
        s0 = DistributedBatchSampler(ds, 5, num_replicas=2, rank=0)
        s1 = DistributedBatchSampler(ds, 5, num_replicas=2, rank=1)
        i0 = [i for b in s0 for i in b]
        i1 = [i for b in s1 for i in b]
        assert len(i0) == len(i1) == 10
        assert not set(i0) & set(i1)
