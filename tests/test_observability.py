"""Serving-stack observability (paddle_tpu/observability/): metrics
contract (schema stability, percentile monotonicity), request-lifecycle
timelines + chrome-trace export, retrace watchdog, stall diagnostics,
and the disabled-mode zero-overhead guarantee. The acceptance bar: a
30-request stream with observability ENABLED reports full latency
distributions and per-step gauges while greedy output stays
bit-identical and steady state stays 1 decode program + <=1 trace per
prefill bucket."""
import json
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.models import llama
from paddle_tpu.inference import (GenerationConfig, ServingEngine,
                                  generate)
from paddle_tpu.observability import (Histogram, Observability,
                                      RetraceWatchdog, TelemetryConfig,
                                      TelemetryPlane)
from paddle_tpu.observability import timeline as timeline_mod

CFG = llama.LlamaConfig(vocab_size=97, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        max_position_embeddings=128, dtype=jnp.float32,
                        remat=False)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _engine(params, **kw):
    kw.setdefault("capacity", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("max_seq_len", 64)
    return ServingEngine(params, CFG, **kw)


# -- metrics primitives ------------------------------------------------

def test_histogram_percentile_monotonicity():
    vals = np.random.RandomState(0).lognormal(1.0, 2.0, 5000)
    h = Histogram()
    for v in vals:
        h.observe(float(v))
    s = h.snapshot()
    assert s["count"] == 5000
    assert s["min"] <= s["p50"] <= s["p95"] <= s["p99"] <= s["max"]
    # resolution: percentiles within ~one bucket (~9%) of exact
    for q, key in ((50, "p50"), (95, "p95"), (99, "p99")):
        exact = float(np.percentile(vals, q))
        assert s[key] == pytest.approx(exact, rel=0.10), (key, exact)


def test_histogram_edge_cases():
    h = Histogram()
    assert h.snapshot()["p99"] == 0.0          # empty
    h.observe(0.0)                             # zero bucket
    h.observe(-1.0)
    h.observe(5.0)
    s = h.snapshot()
    assert s["count"] == 3 and s["min"] == -1.0 and s["max"] == 5.0
    assert 0.0 <= s["p50"] <= s["p95"] <= s["p99"] <= 5.0


# -- metrics schema contract -------------------------------------------

BASE_KEYS = {
    "decode_traces", "prefill_traces", "calibration_traces",
    "decode_steps", "prefill_chunks", "prefill_tokens",
    "live_slot_steps", "tokens_generated", "requests_submitted",
    "requests_completed", "drain_truncations", "wall_time_s",
    "tokens_per_sec", "prefill_tokens_per_sec", "ttft_ms_mean",
    "ttft_ms_max", "slot_utilization",
    "decode_variant",        # r11: fused decode-block dispatch report
    # r15: SLO-aware admission (preempt/requeue counters + the
    # per-class queue-wait / slo_attainment scheduler report)
    "preemptions", "requeues", "deadline_expired", "scheduler",
    # r16: host-RAM KV offload tier (spill extract / restore insert
    # traces + bytes each direction; zeros without kv_offload)
    "offload_traces", "kv_spill_bytes", "kv_restore_bytes",
    # r17: fused prefill-block dispatch report + the bucket-pad rows
    # fed to prefill chunks (the compute the ragged fused kernels skip)
    "prefill_variant", "prefill_pad_tokens",
    # r18: weight-quantization dispatch report ({"mode": "off"} on fp
    # engines; mode/weight_dtype/attn/mlp on weight_quant engines —
    # trace-time snapshot, the decode_variant contract)
    "weight_quant_variant",
    # r21: roofline observatory (per-variant modeled bytes/step + the
    # bandwidth-bound step-time floor; present in BOTH obs modes)
    "roofline",
}
OBS_KEYS = {"latency", "gauges", "retrace_warnings", "stall_dumps",
            "timeline_events", "timeline_dropped"}
LATENCY_KEYS = {"ttft_ms", "tpot_ms", "queue_wait_ms", "e2e_ms",
                "prefill_chunk_ms", "decode_step_ms", "step_ms"}
HIST_KEYS = {"count", "unit", "mean", "min", "max", "p50", "p95", "p99"}


def _run_stream(eng, n=4, seed=0, max_new=4):
    rng = np.random.RandomState(seed)
    rs = [eng.submit(rng.randint(0, 97, (int(s),)).astype(np.int32),
                     GenerationConfig(max_new_tokens=max_new,
                                      greedy=True))
          for s in rng.randint(4, 14, n)]
    eng.drain()
    return rs


def test_metrics_schema_frozen_disabled(params):
    """The metric key set is a CONTRACT: bench output and downstream
    parsers rely on it. Extend deliberately (update this test), never
    by accident."""
    eng = _engine(params)
    _run_stream(eng)
    m = eng.metrics()
    assert set(m.keys()) == BASE_KEYS
    # r20: decode_variant gained the single-launch "block" slot beside
    # the per-stage names — extended, not loosened
    assert set(m["decode_variant"].keys()) == {"mode", "block", "attn",
                                               "mlp"}
    assert m["decode_variant"]["block"] in ("pallas_block", "composed")
    assert m["weight_quant_variant"] == {"mode": "off"}


def test_metrics_schema_frozen_enabled(params):
    eng = _engine(params, observability=True)
    _run_stream(eng)
    m = eng.metrics()
    assert set(m.keys()) == BASE_KEYS | OBS_KEYS
    assert set(m["decode_variant"].keys()) == {"mode", "block", "attn",
                                               "mlp"}
    assert m["decode_variant"]["block"] in ("pallas_block", "composed")
    assert set(m["latency"].keys()) == LATENCY_KEYS
    for name, snap in m["latency"].items():
        assert set(snap.keys()) == HIST_KEYS, name
    # engine-run percentile monotonicity on the real TTFT data
    t = m["latency"]["ttft_ms"]
    assert t["count"] == 4
    assert t["p50"] <= t["p95"] <= t["p99"] <= t["max"]
    # prefix-cache engines add exactly the prefix_cache sub-dict;
    # telemetry (r22) adds exactly the telemetry sub-dict, itself a
    # frozen sub-schema
    eng2 = _engine(params, prefix_cache=True, observability=True,
                   telemetry=TelemetryConfig(sample_every=2,
                                             detectors=()))
    _run_stream(eng2)
    m2 = eng2.metrics()
    assert set(m2.keys()) == \
        BASE_KEYS | OBS_KEYS | {"prefix_cache", "telemetry"}
    assert set(m2["telemetry"].keys()) == {"samples", "series",
                                           "alerts", "rules"}
    assert set(m2["telemetry"]["alerts"].keys()) == {"page", "ticket"}
    assert m2["telemetry"]["samples"] >= 1
    assert m2["telemetry"]["series"] > 0
    # the scheduler section carries the raw SLO counters the burn-rate
    # windows difference (r22)
    assert set(m2["scheduler"].keys()) == {
        "per_class", "slo_attainment", "slo_seen", "slo_attained",
        "queue_depth"}


def test_metrics_schema_frozen_tp(params):
    """Mesh'd engines extend the frozen schema by exactly "mesh"
    (always) and "collectives" (observability on — the bound flight
    recorder's structured sub-dict); the raw recorder counters must
    never leak as top-level keys in either mode."""
    from paddle_tpu.inference import ServingMesh
    mesh = ServingMesh.make(tp=2, collective="psum")
    eng = _engine(params, mesh=mesh)                 # disabled mode
    _run_stream(eng)
    m = eng.metrics()
    assert set(m.keys()) == BASE_KEYS | {"mesh"}
    assert set(m["mesh"].keys()) == {"axis", "tp", "collective"}
    eng2 = _engine(params, mesh=mesh, observability=True)
    _run_stream(eng2)
    m2 = eng2.metrics()
    assert set(m2.keys()) == BASE_KEYS | OBS_KEYS | {"mesh",
                                                     "collectives"}
    assert set(m2["collectives"].keys()) == {"calls", "bytes",
                                             "latency_ms"}
    assert set(m2["latency"].keys()) == LATENCY_KEYS
    assert m2["collectives"]["calls"]["psum@tp"] > 0
    for hist in m2["collectives"]["latency_ms"].values():
        assert set(hist.keys()) == HIST_KEYS


@pytest.mark.roofline
def test_metrics_roofline_schema(params):
    """The roofline sub-dict (r21) is schema-stable in BOTH obs modes:
    per-arm modeled bytes/step + the bandwidth-bound step-time floor,
    the labelled peak pair, the active dispatch arm and layer count."""
    for obs in (False, True):
        eng = _engine(params, observability=obs)
        _run_stream(eng)
        roof = eng.metrics()["roofline"]
        assert set(roof.keys()) == {"variants", "peak_hbm_bw",
                                    "peak_source", "active", "layers"}
        assert set(roof["variants"].keys()) == {"pallas_block",
                                                "pallas_fused",
                                                "unfused"}
        for row in roof["variants"].values():
            assert set(row.keys()) == {"bytes_per_step",
                                       "step_us_at_peak_bw",
                                       "achieved_bw_frac"}
            assert row["bytes_per_step"] > 0
            assert row["step_us_at_peak_bw"] > 0
        assert roof["active"] in roof["variants"]
        assert roof["layers"] >= 1
        # the single-launch arm re-streams MLP tiles per batch row, so
        # its modeled step traffic can never undercut the two-kernel arm
        assert roof["variants"]["pallas_block"]["bytes_per_step"] >= \
            roof["variants"]["pallas_fused"]["bytes_per_step"]
        # only the obs-enabled engine has a measured mean to attribute
        if obs:
            act = roof["variants"][roof["active"]]
            assert act["achieved_bw_frac"] is not None


def test_gauges_sampled_each_step(params):
    eng = _engine(params, prefix_cache=True, observability=True)
    _run_stream(eng, n=3)
    g = eng.metrics()["gauges"]
    for key in ("pages_free", "pages_in_use", "kv_refcount_total",
                "queue_depth", "live_slots", "prefix_tree_pages",
                "prefix_hit_ratio"):
        assert key in g, key
        assert g[key]["last"] is not None
    # the series saw real allocator pressure over time (tree-held pages
    # keep pages_in_use high at the end, so >= not >)
    assert len(eng.observability.registry.gauges["pages_free"].series) > 0
    assert g["pages_in_use"]["max"] >= g["pages_in_use"]["last"] > 0


# -- satellites ---------------------------------------------------------

def test_reset_metrics_excludes_warmup_ttft(params):
    """A request in flight across reset_metrics() must not leak its
    warmup-measured TTFT into the post-reset window."""
    eng = _engine(params, capacity=2)
    rng = np.random.RandomState(7)
    # r1 decodes long enough to stay in flight across the reset
    r1 = eng.submit(rng.randint(0, 97, (6,)).astype(np.int32),
                    GenerationConfig(max_new_tokens=12, greedy=True))
    for _ in range(3):
        eng.step()
    assert r1.ttft is not None and not r1.done
    eng.reset_metrics()
    m = eng.metrics()
    assert m["ttft_ms_mean"] is None       # r1's TTFT is warmup data
    r2 = eng.submit(rng.randint(0, 97, (5,)).astype(np.int32),
                    GenerationConfig(max_new_tokens=2, greedy=True))
    eng.drain()
    m = eng.metrics()
    assert r2.ttft is not None
    # only r2's post-reset TTFT counts (metrics rounds to 3 decimals)
    assert m["ttft_ms_mean"] == round(r2.ttft * 1e3, 3)
    assert m["ttft_ms_max"] == round(r2.ttft * 1e3, 3)


def test_reset_metrics_excludes_warmup_ttft_from_histograms(params):
    """The ttft_ms HISTOGRAM must apply the same warmup exclusion as
    ttft_ms_mean/max — the two must never disagree in one snapshot."""
    eng = _engine(params, capacity=2, observability=True)
    rng = np.random.RandomState(7)
    r1 = eng.submit(rng.randint(0, 97, (6,)).astype(np.int32),
                    GenerationConfig(max_new_tokens=12, greedy=True))
    for _ in range(3):
        eng.step()
    assert r1.ttft is not None and not r1.done
    eng.reset_metrics()
    eng.drain()                     # r1 finishes post-reset
    m = eng.metrics()
    assert m["ttft_ms_mean"] is None
    assert m["latency"]["ttft_ms"]["count"] == 0
    # the JSONL record survives, flagged as warmup
    recs = list(eng.observability.request_records)
    assert len(recs) == 1 and recs[0].get("warmup") is True


def test_prefill_tokens_per_sec(params):
    eng = _engine(params)
    rng = np.random.RandomState(8)
    total_prompt = 0
    for s in (5, 9, 13):
        eng.submit(rng.randint(0, 97, (s,)).astype(np.int32),
                   GenerationConfig(max_new_tokens=3, greedy=True))
        total_prompt += s
    eng.drain()
    m = eng.metrics()
    assert eng.counters["prefill_tokens"] == total_prompt
    assert m["prefill_tokens_per_sec"] > 0
    # consistency: tokens/s ratios match the raw counters
    assert (m["prefill_tokens_per_sec"] / m["tokens_per_sec"]) == \
        pytest.approx(total_prompt / m["tokens_generated"], rel=0.01)


def test_drain_truncation_observable(params):
    eng = _engine(params)
    rng = np.random.RandomState(9)
    eng.submit(rng.randint(0, 97, (8,)).astype(np.int32),
               GenerationConfig(max_new_tokens=10, greedy=True))
    n = eng.drain(max_steps=2)
    assert n == 2
    assert eng.last_drain_truncated is True
    assert eng.counters["drain_truncations"] == 1
    assert not eng.idle
    n2 = eng.drain()                       # clean drain resets the flag
    assert n2 > 0 and eng.last_drain_truncated is False
    assert eng.idle
    assert eng.counters["drain_truncations"] == 1
    # a drain that finishes exactly AT max_steps is NOT a truncation
    eng.submit(rng.randint(0, 97, (4,)).astype(np.int32),
               GenerationConfig(max_new_tokens=2, greedy=True))
    probe = eng.drain()
    eng.submit(rng.randint(0, 97, (4,)).astype(np.int32),
               GenerationConfig(max_new_tokens=2, greedy=True))
    assert eng.drain(max_steps=probe) == probe
    assert eng.last_drain_truncated is False


# -- retrace watchdog ---------------------------------------------------

def test_watchdog_unit():
    wd = RetraceWatchdog(warn=False)
    c = {"decode_traces": 1, "calibration_traces": 0,
         "prefill_traces": {8: 1}}
    assert wd.check(c) == 0                # not armed yet
    wd.mark_warmup(c)
    assert wd.check(c) == 0                # clean
    c["decode_traces"] += 1
    c["prefill_traces"][16] = 1
    assert wd.check(c) == 2
    assert wd.check(c) == 0                # baseline advanced: warn once


def test_watchdog_fires_on_forced_retrace(params):
    """Warm up bucket 8 only, reset (arms the watchdog), then submit a
    prompt needing bucket 16 — a genuinely new prefill program after
    warmup, exactly what the watchdog exists to catch."""
    eng = _engine(params, observability=True)
    rng = np.random.RandomState(10)
    eng.submit(rng.randint(0, 97, (6,)).astype(np.int32),
               GenerationConfig(max_new_tokens=2, greedy=True))
    eng.drain()
    eng.reset_metrics()
    assert eng.observability.watchdog.armed
    eng.submit(rng.randint(0, 97, (14,)).astype(np.int32),
               GenerationConfig(max_new_tokens=2, greedy=True))
    with pytest.warns(RuntimeWarning, match="retrace after warmup"):
        eng.drain()
    m = eng.metrics()
    assert m["retrace_warnings"] >= 1
    assert any(e["program"] == "prefill[16]"
               for e in eng.observability.watchdog.events)
    # steady traffic on warmed buckets stays silent
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error", RuntimeWarning)
        eng.submit(rng.randint(0, 97, (6,)).astype(np.int32),
                   GenerationConfig(max_new_tokens=2, greedy=True))
        eng.drain()


# -- stall diagnostics --------------------------------------------------

def test_stall_dump_on_starved_drain(params, tmp_path):
    """An engine starved by an undersized pool must leave a flight-
    recorder dump: scheduler snapshot + timeline tail, as JSON."""
    dump = tmp_path / "stall.json"
    obs = Observability(stall_dump_path=str(dump))
    eng = _engine(params, num_blocks=10, observability=obs)
    rng = np.random.RandomState(11)
    # hold 7 of the 9 usable pages hostage via a foreign allocation so
    # the queued request (needs 6 pages) can never admit
    eng.mgr.allocate(999, 7 * 4)
    eng.submit(rng.randint(0, 97, (20,)).astype(np.int32),
               GenerationConfig(max_new_tokens=4, greedy=True))
    with pytest.raises(RuntimeError, match="starved") as ei:
        eng.drain()
    assert str(dump) in str(ei.value)      # the error names the dump
    report = json.loads(dump.read_text())
    assert report["reason"].startswith("drain starved")
    sched = report["scheduler"]
    assert sched["queue_depth"] == 1
    assert sched["queued"][0]["need_pages"] == 6
    assert sched["pages_free"] == 2
    assert all(s["phase"] == "idle" for s in sched["slots"])
    assert any(e["name"] == "submit" for e in report["timeline_tail"])
    assert eng.metrics()["stall_dumps"] == 1


def test_step_deadline_dump(params, tmp_path):
    dump = tmp_path / "deadline.json"
    obs = Observability(step_deadline_s=0.0, stall_dump_path=str(dump))
    eng = _engine(params, observability=obs)
    rng = np.random.RandomState(12)
    eng.submit(rng.randint(0, 97, (5,)).astype(np.int32),
               GenerationConfig(max_new_tokens=2, greedy=True))
    eng.step()                             # any real step blows a 0s deadline
    assert dump.exists()
    assert "deadline" in json.loads(dump.read_text())["reason"]
    assert eng.metrics()["stall_dumps"] >= 1


# -- disabled mode: zero overhead --------------------------------------

def test_disabled_mode_allocates_no_event_objects(params, monkeypatch):
    """observability=False must not allocate a single TimelineEvent or
    Observability object anywhere in the serving loop."""
    def boom(*a, **k):
        raise AssertionError("event object allocated in disabled mode")
    monkeypatch.setattr(timeline_mod.TimelineEvent, "__init__", boom)
    monkeypatch.setattr(Observability, "__init__", boom)
    monkeypatch.setattr(TelemetryPlane, "__init__", boom)
    eng = _engine(params)
    assert eng.observability is None
    assert eng.telemetry is None
    rs = _run_stream(eng, n=3, seed=13)
    assert all(r.done for r in rs)
    m = eng.metrics()
    assert "latency" not in m and "gauges" not in m
    assert "telemetry" not in m
    with pytest.raises(RuntimeError, match="disabled"):
        eng.export_trace("/tmp/never.json")


# -- acceptance: full stream with observability on ---------------------

def test_enabled_stream_parity_traces_and_exports(params, tmp_path):
    """30-request mixed-arrival stream with observability ENABLED:
    greedy outputs stay bit-identical to generate(), steady state stays
    1 decode program + <=1 trace per prefill bucket, latency/gauge
    distributions are populated, and the chrome trace + JSONL exports
    are valid."""
    rng = np.random.RandomState(14)
    eng = _engine(params, capacity=3, observability=True)
    pending = []
    for i in range(30):
        S, N = int(rng.randint(3, 17)), int(rng.randint(2, 7))
        pending.append((rng.randint(0, 97, (S,)).astype(np.int32),
                        GenerationConfig(max_new_tokens=N, greedy=True)))
    submitted = []
    while pending or not eng.idle:
        for _ in range(min(len(pending), 1 + int(rng.randint(0, 3)))):
            p, g = pending.pop(0)
            submitted.append((p, g, eng.submit(p, g)))
        eng.step()
    assert len(submitted) == 30
    c = eng.counters
    assert c["decode_traces"] == 1, c
    assert all(n <= 1 for n in c["prefill_traces"].values()), c
    # bit-identical greedy output vs single-request generate()
    for p, g, r in submitted[:5]:
        want = np.asarray(generate(params, jnp.asarray(p)[None], CFG,
                                   g))[0, p.size:]
        np.testing.assert_array_equal(np.asarray(r.tokens), want)
    m = eng.metrics()
    lat = m["latency"]
    assert lat["ttft_ms"]["count"] == 30
    assert lat["tpot_ms"]["count"] > 0
    assert lat["queue_wait_ms"]["count"] == 30
    for name in ("ttft_ms", "tpot_ms", "queue_wait_ms"):
        s = lat[name]
        assert s["p50"] <= s["p95"] <= s["p99"], name
    assert m["gauges"]["pages_free"]["last"] is not None
    assert m["retrace_warnings"] == 0
    # chrome trace: valid json, per-request spans + counter tracks
    trace_path = tmp_path / "trace.json"
    eng.export_trace(str(trace_path))
    trace = json.loads(trace_path.read_text())
    evs = trace["traceEvents"]
    assert any(e.get("ph") == "X" and "decode" in e.get("name", "")
               for e in evs)
    assert any(e.get("ph") == "C" and e.get("name") == "pages_free"
               for e in evs)
    names = {e["name"] for e in evs if e.get("ph") == "X"}
    assert any(n.startswith("req") and n.endswith(":prefill")
               for n in names)
    # JSONL: meta + events + 30 request records; trace_summary parses it
    jsonl_path = tmp_path / "tl.jsonl"
    eng.write_timeline(str(jsonl_path))
    recs = [json.loads(ln)
            for ln in jsonl_path.read_text().splitlines()]
    kinds = [r["kind"] for r in recs]
    assert kinds[0] == "meta"
    assert kinds.count("request") == 30
    assert kinds.count("event") > 30
    # r20: every decode_step event carries its serving variant so
    # trace_summary can attribute decode time per implementation
    dsteps = [r for r in recs
              if r["kind"] == "event" and r["name"] == "decode_step"]
    assert dsteps
    assert all(r.get("decode_variant") in ("pallas_block",
                                           "pallas_fused", "unfused")
               for r in dsteps)
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import trace_summary
    finally:
        sys.path.pop(0)
    meta, events, requests = trace_summary.load(str(jsonl_path))
    summary = trace_summary.summarize(meta, events, requests, top=5)
    assert summary["requests"] == 30
    assert "decode_step" in summary["phases"]
    # r20 per-variant decode attribution: one bucket per variant seen,
    # counts covering every stamped decode_step event
    dec = summary["decode"]["variants"]
    assert set(dec) <= {"pallas_block", "pallas_fused", "unfused"}
    assert sum(v["count"] for v in dec.values()) == len(dsteps)
    # r21: arms the meta roofline header models also carry modeled
    # bytes/step + the peak-BW step-time floor (and the measured/floor
    # ratio when the mean is nonzero)
    for v in dec.values():
        assert {"count", "total_ms", "max_ms", "mean_ms",
                "bytes_per_step_modeled",
                "step_us_at_peak_bw"} <= set(v.keys())
        assert v["bytes_per_step_modeled"] > 0
        assert v["step_us_at_peak_bw"] > 0
    assert len(summary["slowest_steps"]) == 5
    r = summary["request_latency"]["ttft_ms"]
    assert r["p50"] <= r["p95"] <= r["p99"] <= r["max"]
    text = trace_summary.render(summary)
    assert "decode_step" in text and "ttft_ms" in text
