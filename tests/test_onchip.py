"""On-chip regression tier (reference analog: the device-gated suites
under test/xpu/ and test/custom_runtime/ — run only when the real
accelerator is reachable).

These tests run ONLY when the axon/TPU backend is live; on the CPU test
mesh (or a wedged tunnel) they skip. They pin the on-chip behaviors
this round debugged the hard way:
- Mosaic compiles the whole Pallas pack (not interpret mode),
- the Trainer step is device-bound (no blocking per-step h2d),
- the fused multi-tensor AdamW path activates on a single-chip mesh.

Run explicitly:  python -m pytest tests/test_onchip.py -q --no-header
(the module must NOT import through conftest's CPU forcing — it spawns
a fresh subprocess per test for an unforced backend).
"""
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow


def _run_on_chip(code, timeout=600):
    """Run `code` in a fresh python with the default (axon) platform.
    Returns (rc, stdout, stderr); skips the caller on tunnel wedge."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    try:
        p = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True, env=env,
                           cwd=ROOT)
    except subprocess.TimeoutExpired:
        pytest.skip("TPU tunnel wedged (probe timeout)")
    return p.returncode, p.stdout, p.stderr


PROBE = """
import jax
d = jax.devices()[0]
assert d.platform in ("tpu", "axon"), d.platform
print("PROBE_OK", d)
"""


_PROBE_RESULT = {}


def _require_chip():
    if "ok" not in _PROBE_RESULT:   # one probe per test run, not per test
        try:
            rc, out, err = _run_on_chip(PROBE, timeout=120)
        except BaseException:
            # _run_on_chip skips on tunnel wedge — record it first or
            # every subsequent test re-pays the full probe timeout
            _PROBE_RESULT["ok"] = False
            _PROBE_RESULT["rc"] = "wedge"
            raise
        _PROBE_RESULT["ok"] = rc == 0 and "PROBE_OK" in out
        _PROBE_RESULT["rc"] = rc
    if not _PROBE_RESULT["ok"]:
        pytest.skip(f"no live TPU backend (rc={_PROBE_RESULT['rc']})")


def test_pallas_pack_compiles_on_chip():
    _require_chip()
    rc, out, err = _run_on_chip("""
import jax, jax.numpy as jnp, json
from paddle_tpu.ops.pallas.flash_attention import flash_attention_pallas
from paddle_tpu.ops.pallas.norms import rms_norm_pallas
from paddle_tpu.ops.pallas.fused_adamw import fused_adamw
from paddle_tpu.ops.pallas._util import interpret_mode
assert not interpret_mode(), "must be compiled, not interpreted"
k = jax.random.PRNGKey(0)
q = jax.random.normal(k, (2, 1024, 4, 128), jnp.bfloat16)
o = jax.block_until_ready(jax.jit(
    lambda q: flash_attention_pallas(q, q, q, causal=True))(q))
g = jax.block_until_ready(jax.jit(jax.grad(
    lambda q: flash_attention_pallas(q, q, q, causal=True)
    .astype(jnp.float32).sum()))(q))
x = jax.random.normal(k, (1024, 4096), jnp.bfloat16)
r = jax.block_until_ready(jax.jit(rms_norm_pallas)(
    x, jnp.ones((4096,), jnp.bfloat16)))
p = jax.random.normal(k, (131072,), jnp.float32)
u = jax.block_until_ready(jax.jit(
    lambda p: fused_adamw(p, p * 0.01, p * 0, p * 0, 1e-3, 1.0))(p))
print("PACK_OK")
""")
    assert rc == 0 and "PACK_OK" in out, (out, err[-2000:])


def test_trainer_step_is_device_bound():
    """Per-step wall time must be close to device time: a blocking h2d
    in the step plumbing (the round-4 llama bug) costs ~1s/step through
    the tunnel and fails the 4x bound."""
    _require_chip()
    rc, out, err = _run_on_chip("""
import os, time, numpy as np, jax, jax.numpy as jnp, json
from paddle_tpu.models.llama import (LlamaConfig, init_params, loss_fn,
                                     param_shardings)
from paddle_tpu.distributed.trainer import MeshConfig, Trainer, make_mesh
cfg = LlamaConfig(vocab_size=8192, hidden_size=512, intermediate_size=1024,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=4, max_position_embeddings=512)
mesh = make_mesh(MeshConfig())
params = init_params(cfg, jax.random.PRNGKey(0))
tr = Trainer(lambda p,t,l: loss_fn(p,t,l,cfg), mesh,
             param_shardings(mesh, cfg), lr=1e-4)
st = tr.init_state(params)
assert tr._fused, "fused AdamW must auto-activate on a 1-chip mesh"
toks = jnp.asarray(np.random.randint(0, 8192, (2, 512)), jnp.int32)
labels = jnp.roll(toks, -1, axis=1)
st, m = tr.step(st, toks, labels)
np.asarray(jnp.ravel(m["loss"])[0])          # compile + sync
t0 = time.perf_counter()
for _ in range(10):
    st, m = tr.step(st, toks, labels)
np.asarray(jnp.ravel(m["loss"])[0])
per_step = (time.perf_counter() - t0) / 10
print("STEP_MS", per_step * 1e3)
# env-overridable bound: the absolute value depends on chip generation
# and tunnel latency; a healthy-but-slower environment should loosen
# it (ONCHIP_STEP_BOUND_S) rather than fail the plumbing check
bound = float(os.environ.get("ONCHIP_STEP_BOUND_S", "0.25"))
assert per_step < bound, \
    f"step plumbing not device-bound: {per_step}s >= {bound}s"
print("DEVBOUND_OK")
""")
    assert rc == 0 and "DEVBOUND_OK" in out, (out, err[-2000:])


def test_int8_paged_decode_on_chip():
    """int8 static-KV serving path compiles + runs on the real chip:
    logits track the bf16 cache within quant tolerance."""
    _require_chip()
    rc, out, err = _run_on_chip("""
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.inference import generation as G
from paddle_tpu.models.llama import LlamaConfig, init_params
from paddle_tpu.ops.paged_attention import quantize_pools
cfg = LlamaConfig(vocab_size=512, hidden_size=256, intermediate_size=512,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=4, max_position_embeddings=128)
params = init_params(cfg, jax.random.PRNGKey(0))
B, S, BS, MB = 2, 32, 16, 8
kc, vc = G.init_cache(cfg, B, MB * BS)
toks = jnp.asarray(np.random.RandomState(0).randint(0, 512, (B, S)),
                   jnp.int32)
logits, kc, vc = G.cached_forward(params, toks, cfg, kc, vc, 0)
L, KV, hd = 2, 4, cfg.head_dim
NB = B * MB
kp = jnp.reshape(kc, (L, NB, BS, KV, hd))
vp = jnp.reshape(vc, (L, NB, BS, KV, hd))
tables = jnp.asarray(np.arange(NB).reshape(B, MB), jnp.int32)
lens = jnp.full((B,), S, jnp.int32)
tok = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
lg_bf, _, _ = G._paged_decode_step(params, tok, cfg, kp, vp, tables, lens)
kq, vq, ks, vs = jax.vmap(quantize_pools)(kp, vp)
lg_i8, _, _ = G._paged_decode_step(params, tok, cfg, kq, vq, tables,
                                   lens, kv_scales=(ks, vs))
rel = float(jnp.max(jnp.abs(lg_i8.astype(jnp.float32)
                            - lg_bf.astype(jnp.float32)))
            / (jnp.max(jnp.abs(lg_bf.astype(jnp.float32))) + 1e-9))
a = np.asarray(jnp.ravel(lg_i8)[0])   # tunnel-safe sync
assert rel < 0.1, rel
print("INT8_PAGED_OK", rel)
""")
    assert rc == 0 and "INT8_PAGED_OK" in out, (out, err[-2000:])


def test_fused_mixed_dtype_trainer_on_chip():
    """The mixed bf16+fp32 llama tree must take the FUSED AdamW path on
    the real chip (the round-5 fix; the old single-dtype check silently
    fell back to the slow per-leaf update)."""
    _require_chip()
    rc, out, err = _run_on_chip("""
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.models.llama import (LlamaConfig, init_params, loss_fn,
                                     param_shardings)
from paddle_tpu.distributed.trainer import MeshConfig, Trainer, make_mesh
cfg = LlamaConfig(vocab_size=4096, hidden_size=512, intermediate_size=1024,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=4, max_position_embeddings=256)
params = init_params(cfg, jax.random.PRNGKey(0))
dts = sorted({str(v.dtype) for v in jax.tree_util.tree_leaves(params)})
assert "float32" in dts and len(dts) == 2, dts   # genuinely mixed
mesh = make_mesh(MeshConfig())
tr = Trainer(lambda p, t, l: loss_fn(p, t, l, cfg), mesh,
             param_shardings(mesh, cfg), lr=1e-4,
             moment_dtype=jnp.bfloat16)
st = tr.init_state(params)
assert tr._fused, "mixed-dtype tree must take the fused path on chip"
toks = jnp.asarray(np.random.RandomState(0).randint(0, 4096, (2, 256)),
                   jnp.int32)
st, m = tr.step(st, toks, jnp.roll(toks, -1, -1))
l0 = float(np.asarray(jnp.ravel(m["loss"])[0]))
for _ in range(4):
    st, m = tr.step(st, toks, jnp.roll(toks, -1, -1))
l1 = float(np.asarray(jnp.ravel(m["loss"])[0]))
assert np.isfinite(l1) and l1 < l0, (l0, l1)
print("FUSED_MIXED_OK", l0, "->", l1)
""")
    assert rc == 0 and "FUSED_MIXED_OK" in out, (out, err[-2000:])
