"""ONNX export tests (reference analog: paddle2onnx conversion tests).

onnxruntime is not shipped here, so numeric verification runs the
exported ModelProto through the bundled numpy evaluator
(paddle_tpu/onnx/runner.py) and compares with the jax forward.
Serialized field numbers are upstream-exact, so the same files load in
onnx/onnxruntime externally.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static import InputSpec
from paddle_tpu.onnx import export
from paddle_tpu.onnx import onnx_pb2 as ox
from paddle_tpu.onnx.runner import run_model


def _roundtrip(layer, path, spec, feeds):
    p = export(layer, path, input_spec=spec)
    m = ox.ModelProto()
    with open(p, "rb") as f:
        m.ParseFromString(f.read())
    return m, run_model(m, feeds)


def test_mlp_export_matches_jax(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                        nn.Softmax())
    x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    ref = np.asarray(net(paddle.to_tensor(x)).numpy())
    m, (out,) = _roundtrip(net, str(tmp_path / "mlp"),
                           [InputSpec([2, 8], "float32")], {"x0": x})
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    assert m.opset_import[0].version == 17
    assert m.ir_version == 8
    # weights became initializers, not Constant nodes
    assert len(m.graph.initializer) >= 4


@pytest.mark.slow
def test_cnn_export_matches_jax(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.BatchNorm2D(8),
                        nn.ReLU(), nn.MaxPool2D(2, 2), nn.Flatten(),
                        nn.Linear(8 * 4 * 4, 5))
    net.eval()
    x = np.random.RandomState(1).randn(2, 3, 8, 8).astype(np.float32)
    ref = np.asarray(net(paddle.to_tensor(x)).numpy())
    m, (out,) = _roundtrip(net, str(tmp_path / "cnn"),
                           [InputSpec([2, 3, 8, 8], "float32")], {"x0": x})
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    ops = {n.op_type for n in m.graph.node}
    assert "Conv" in ops and "MaxPool" in ops


@pytest.mark.slow
def test_transformer_block_export_matches_jax(tmp_path):
    paddle.seed(1)

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(50, 16)
            self.ln = nn.LayerNorm(16)
            self.attn = nn.MultiHeadAttention(16, 4)
            self.fc1 = nn.Linear(16, 32)
            self.fc2 = nn.Linear(32, 16)
            self.act = nn.GELU()

        def forward(self, ids):
            h = self.emb(ids)
            h = h + self.attn(self.ln(h), self.ln(h), self.ln(h))
            return self.fc2(self.act(self.fc1(h)))

    blk = Block()
    blk.eval()
    ids = np.random.RandomState(2).randint(0, 50, (2, 6)).astype(np.int64)
    ref = np.asarray(blk(paddle.to_tensor(ids)).numpy())
    m, (out,) = _roundtrip(blk, str(tmp_path / "blk"),
                           [InputSpec([2, 6], "int64")], {"x0": ids})
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    ops = {n.op_type for n in m.graph.node}
    assert "Gather" in ops and "Einsum" in ops and "Erf" in ops


@pytest.mark.slow
def test_resnet18_export_matches_jax(tmp_path):
    from paddle_tpu.vision.models import resnet18
    paddle.seed(0)
    net = resnet18(num_classes=10)
    net.eval()
    x = np.random.RandomState(0).randn(1, 3, 32, 32).astype(np.float32)
    ref = np.asarray(net(paddle.to_tensor(x)).numpy())
    m, (out,) = _roundtrip(net, str(tmp_path / "r18"),
                           [InputSpec([1, 3, 32, 32], "float32")],
                           {"x0": x})
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)
    assert len(m.graph.node) > 100


def test_plain_function_export(tmp_path):
    def f(a, b):
        return (a * b + 1.0).sum(axis=-1)

    a = np.random.RandomState(3).randn(3, 4).astype(np.float32)
    b = np.random.RandomState(4).randn(3, 4).astype(np.float32)
    m, (out,) = _roundtrip(f, str(tmp_path / "fn"),
                           [InputSpec([3, 4], "float32"),
                            InputSpec([3, 4], "float32")],
                           {"x0": a, "x1": b})
    np.testing.assert_allclose(out, (a * b + 1.0).sum(-1), rtol=1e-6)


def test_unsupported_primitive_raises(tmp_path):
    import paddle_tpu.nn.functional as F
    from paddle_tpu.onnx.exporter import UnsupportedOp

    def f(x):
        # sort has no handler -> must fail loudly, not silently mistranslate
        return paddle.sort(x)

    with pytest.raises((UnsupportedOp, NotImplementedError)):
        export(f, str(tmp_path / "bad"),
               input_spec=[InputSpec([4], "float32")])


def test_serialized_bytes_parse_standalone(tmp_path):
    """The on-disk bytes parse with a FRESH protobuf message (no shared
    python state) — the interop property external onnx loaders rely on."""
    net = nn.Sequential(nn.Linear(4, 2))
    p = export(net, str(tmp_path / "m"),
               input_spec=[InputSpec([1, 4], "float32")])
    raw = open(p, "rb").read()
    m = ox.ModelProto()
    m.ParseFromString(raw)
    assert m.producer_name == "paddle_tpu"
    assert m.graph.input[0].type.tensor_type.shape.dim[1].dim_value == 4
    assert m.SerializeToString() == raw


def test_opset_version_threaded_to_model(tmp_path):
    """Advisor fix: a model requested at opset 13 must declare 13 in
    opset_import (the emitted op forms are 13-compatible)."""
    from paddle_tpu import onnx as ponnx
    net = nn.Linear(4, 2)
    p = ponnx.export(net, str(tmp_path / "m13"),
                     input_spec=[InputSpec([1, 4], "float32")],
                     opset_version=13)
    m = ox.ModelProto()
    with open(p, "rb") as f:
        m.ParseFromString(f.read())
    assert m.opset_import[0].version == 13
    with pytest.raises(ValueError):
        ponnx.export(net, str(tmp_path / "bad"),
                     input_spec=[InputSpec([1, 4], "float32")],
                     opset_version=12)
