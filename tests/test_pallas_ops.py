"""Pallas kernel numerics vs XLA references (interpret mode on CPU; the
same kernels compile to Mosaic on TPU). Gate per SURVEY.md §7 step 5."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.flash_attention import _ref_attention
from paddle_tpu.ops.pallas.flash_attention import flash_attention_pallas
from paddle_tpu.ops.pallas.norms import rms_norm_pallas, layer_norm_pallas
from paddle_tpu.ops import rms_norm_ref, layer_norm_ref
from paddle_tpu.ops.rope import apply_rope, build_rope_cache


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_matches_ref(self, causal):
        rng = np.random.RandomState(0)
        b, s, h, d = 2, 128, 2, 64
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        o = flash_attention_pallas(q, k, v, causal=causal)
        ref = _ref_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.slow
    def test_bwd_matches_ref(self):
        rng = np.random.RandomState(1)
        b, s, h, d = 1, 128, 2, 64
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

        def f(q, k, v):
            return jnp.sum(flash_attention_pallas(q, k, v, causal=True) ** 2)

        def g(q, k, v):
            return jnp.sum(_ref_attention(q, k, v, causal=True) ** 2)

        gp = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=5e-5, rtol=5e-4)

    def test_uneven_seq_multiblock(self):
        rng = np.random.RandomState(2)
        b, s, h, d = 1, 1024, 1, 64  # 2 blocks of 512
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        o = flash_attention_pallas(q, q, q, causal=True)
        ref = _ref_attention(q, q, q, causal=True)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   atol=3e-5, rtol=3e-5)


class TestNorms:
    @pytest.mark.slow
    def test_rms_norm_fwd_bwd(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(4, 64, 128), jnp.float32)
        w = jnp.asarray(rng.rand(128) + 0.5, jnp.float32)
        out = rms_norm_pallas(x, w)
        ref = rms_norm_ref(x, w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)
        g1 = jax.grad(lambda x, w: jnp.sum(rms_norm_pallas(x, w) ** 2),
                      argnums=(0, 1))(x, w)
        g2 = jax.grad(lambda x, w: jnp.sum(rms_norm_ref(x, w) ** 2),
                      argnums=(0, 1))(x, w)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_layer_norm(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(8, 128), jnp.float32)
        w = jnp.asarray(rng.rand(128) + 0.5, jnp.float32)
        b = jnp.asarray(rng.randn(128), jnp.float32)
        out = layer_norm_pallas(x, w, b)
        ref = layer_norm_ref(x, w, b)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)


class TestRope:
    def test_rotation_preserves_norm(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 16, 4, 64), jnp.float32)
        out = apply_rope(x)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(out), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)

    def test_relative_property(self):
        """<rope(q,m), rope(k,n)> depends only on m-n."""
        rng = np.random.RandomState(0)
        d = 32
        q = jnp.asarray(rng.randn(1, 1, 1, d), jnp.float32)
        k = jnp.asarray(rng.randn(1, 1, 1, d), jnp.float32)
        sin, cos = build_rope_cache(64, d)

        def at(x, pos):
            return apply_rope(x, sin, cos,
                              position_ids=jnp.asarray([[pos]]))[0, 0, 0]

        d1 = float(jnp.dot(at(q, 5), at(k, 3)))
        d2 = float(jnp.dot(at(q, 12), at(k, 10)))
        assert abs(d1 - d2) < 1e-3

    def test_position_ids_gather(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(1, 4, 2, 32), jnp.float32)
        full = apply_rope(x)
        pid = apply_rope(x, position_ids=jnp.asarray([[0, 1, 2, 3]]))
        np.testing.assert_allclose(np.asarray(full), np.asarray(pid),
                                   atol=1e-6)


class TestFusedAdamW:
    def test_matches_formula(self):
        from paddle_tpu.ops.pallas.fused_adamw import fused_adamw
        rng = np.random.RandomState(0)
        n = 256
        p = jnp.asarray(rng.randn(n), jnp.float32)
        g = jnp.asarray(rng.randn(n), jnp.float32)
        m = jnp.zeros(n, jnp.float32)
        v = jnp.zeros(n, jnp.float32)
        p2, m2, v2 = fused_adamw(p, g, m, v, lr=0.1, step=1.0,
                                 weight_decay=0.01)
        m_ref = 0.1 * np.asarray(g)
        v_ref = 0.001 * np.asarray(g) ** 2
        mhat = m_ref / (1 - 0.9)
        vhat = v_ref / (1 - 0.999)
        p_ref = np.asarray(p) * (1 - 0.1 * 0.01) - \
            0.1 * mhat / (np.sqrt(vhat) + 1e-8)
        np.testing.assert_allclose(np.asarray(p2), p_ref, rtol=1e-5,
                                   atol=1e-6)

    def test_prime_length_pads_not_degrades(self):
        # awkward (prime) n must pad to a block multiple, not fall back
        # to block=1 with an n-wide sequential grid; outputs keep n
        from paddle_tpu.ops.pallas.fused_adamw import fused_adamw
        rng = np.random.RandomState(1)
        n = 1009  # prime
        p = jnp.asarray(rng.randn(n), jnp.float32)
        g = jnp.asarray(rng.randn(n), jnp.float32)
        m = jnp.zeros(n, jnp.float32)
        v = jnp.zeros(n, jnp.float32)
        p2, m2, v2 = fused_adamw(p, g, m, v, lr=0.1, step=1.0,
                                 weight_decay=0.01)
        assert p2.shape == (n,) and m2.shape == (n,) and v2.shape == (n,)
        m_ref = 0.1 * np.asarray(g)
        vhat = (0.001 * np.asarray(g) ** 2) / (1 - 0.999)
        p_ref = np.asarray(p) * (1 - 0.1 * 0.01) - \
            0.1 * (m_ref / (1 - 0.9)) / (np.sqrt(vhat) + 1e-8)
        np.testing.assert_allclose(np.asarray(p2), p_ref, rtol=1e-5,
                                   atol=1e-6)


class TestNormRowPadding:
    def test_rms_prime_rows(self):
        from paddle_tpu.ops.pallas.norms import rms_norm_pallas
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(1, 127, 256), jnp.float32)  # prime rows
        w = jnp.asarray(rng.randn(256), jnp.float32)
        o = rms_norm_pallas(x, w)
        xf = np.asarray(x)
        ref = xf / np.sqrt((xf ** 2).mean(-1, keepdims=True) + 1e-6) \
            * np.asarray(w)
        assert o.shape == x.shape
        np.testing.assert_allclose(np.asarray(o), ref, atol=2e-5)

    def test_layernorm_prime_rows(self):
        from paddle_tpu.ops.pallas.norms import layer_norm_pallas
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(127, 256), jnp.float32)
        w = jnp.asarray(rng.randn(256), jnp.float32)
        b = jnp.asarray(rng.randn(256), jnp.float32)
        o = layer_norm_pallas(x, w, b)
        xf = np.asarray(x)
        mu = xf.mean(-1, keepdims=True)
        var = xf.var(-1, keepdims=True)
        ref = (xf - mu) / np.sqrt(var + 1e-5) * np.asarray(w) \
            + np.asarray(b)
        assert o.shape == x.shape
        np.testing.assert_allclose(np.asarray(o), ref, atol=2e-5)


class TestFlashAttentionExtended:
    """GQA / segment-id (varlen) / bias capabilities of the Pallas kernel
    (reference varlen path: paddle/phi/kernels/gpu/flash_attn_kernel.cu:137)."""

    def _qkv(self, b=2, s=256, h=4, kvh=2, d=64, seed=0):
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, kvh, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, kvh, d), jnp.float32)
        return q, k, v

    @pytest.mark.slow
    @pytest.mark.parametrize("causal", [False, True])
    def test_gqa_matches_ref(self, causal):
        q, k, v = self._qkv(kvh=1)
        o = flash_attention_pallas(q, k, v, causal=causal)
        ref = _ref_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.slow
    def test_bias_fwd_bwd(self):
        q, k, v = self._qkv(h=2, kvh=2, s=128)
        rng = np.random.RandomState(3)
        bias = jnp.asarray(rng.randn(1, 2, 128, 128) * 0.5, jnp.float32)

        def lp(q, k, v, b):
            return jnp.sum(flash_attention_pallas(q, k, v, causal=True,
                                                  bias=b,
                                                  bias_grad=True) ** 2)

        def lr(q, k, v, b):
            return jnp.sum(_ref_attention(q, k, v, causal=True,
                                          bias=b) ** 2)

        gp = jax.grad(lp, argnums=(0, 1, 2, 3))(q, k, v, bias)
        gr = jax.grad(lr, argnums=(0, 1, 2, 3))(q, k, v, bias)
        for a, b_ in zip(gp, gr):
            scale = float(jnp.abs(b_).max()) + 1e-9
            np.testing.assert_allclose(np.asarray(a) / scale,
                                       np.asarray(b_) / scale,
                                       atol=2e-5)

    @pytest.mark.slow
    def test_segment_ids_block_cross_attention(self):
        q, k, v = self._qkv(h=2, kvh=2, s=256, seed=5)
        seg = jnp.asarray(
            np.sort(np.random.RandomState(6).randint(0, 3, (2, 256)),
                    axis=1), jnp.int32)
        o = flash_attention_pallas(q, k, v, causal=True, segment_ids=seg)
        ref = _ref_attention(q, k, v, causal=True, segment_ids=seg)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.slow
    def test_flash_attn_unpadded(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(7)
        lens = [60, 100, 96]
        total, h, d = sum(lens), 2, 64
        cu = np.cumsum([0] + lens).astype(np.int32)
        q = rng.randn(total, h, d).astype(np.float32)
        k = rng.randn(total, h, d).astype(np.float32)
        v = rng.randn(total, h, d).astype(np.float32)
        out, _ = F.flash_attn_unpadded(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            paddle.to_tensor(cu), paddle.to_tensor(cu), causal=True)
        out = np.asarray(out._value)
        # per-sequence reference: attention confined to each span
        for i, (a, b_) in enumerate(zip(cu[:-1], cu[1:])):
            ref = _ref_attention(jnp.asarray(q[None, a:b_]),
                                 jnp.asarray(k[None, a:b_]),
                                 jnp.asarray(v[None, a:b_]), causal=True)
            np.testing.assert_allclose(out[a:b_], np.asarray(ref[0]),
                                       atol=2e-5, rtol=2e-5)


    @pytest.mark.slow
    def test_fully_masked_rows_zero(self):
        # a query whose segment id matches no key must output 0 (not the
        # mean of V) and contribute nothing to dk/dv
        q, k, v = self._qkv(b=1, h=2, kvh=2, s=128, seed=9)
        seg_q = jnp.full((1, 128), 7, jnp.int32).at[0, :64].set(0)
        seg_k = jnp.zeros((1, 128), jnp.int32)
        o = flash_attention_pallas(q, k, v, segment_ids=seg_q,
                                   kv_segment_ids=seg_k)
        np.testing.assert_allclose(np.asarray(o[0, 64:]), 0.0, atol=1e-6)
        ref = _ref_attention(q, k, v, segment_ids=seg_q,
                             kv_segment_ids=seg_k)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

        def lp(kk):
            return jnp.sum(flash_attention_pallas(
                q, k=kk, v=v, segment_ids=seg_q,
                kv_segment_ids=seg_k) ** 2)

        def lr(kk):
            return jnp.sum(_ref_attention(
                q, k=kk, v=v, segment_ids=seg_q,
                kv_segment_ids=seg_k) ** 2)
        gk_p = jax.grad(lp)(k)
        gk_r = jax.grad(lr)(k)
        np.testing.assert_allclose(np.asarray(gk_p), np.asarray(gk_r),
                                   atol=2e-4)


class TestAutotune:
    def test_autotune_sweeps_and_caches(self, tmp_path, monkeypatch):
        from paddle_tpu.ops.pallas import autotune as at
        cache = at.AutotuneCache(str(tmp_path / "tune.json"))
        monkeypatch.setattr(at, "_cache", cache)
        from paddle_tpu.core.flags import GLOBAL_FLAGS
        GLOBAL_FLAGS.set("kernel_autotune", True)
        calls = []

        def build(cfg):
            def fn(x):
                calls.append(cfg)
                import time
                time.sleep(0.02 if cfg == "slow" else 0.0)
                return x + 1
            return fn

        import paddle_tpu.ops.pallas._util as u
        prev = u._FORCE_INTERPRET
        u.set_force_interpret(False)  # autotune is a no-op in interpret mode
        try:
            cfg = at.autotune("toy|(4,)", ["slow", "fast"], build,
                              (jnp.ones(4),), warmup=1, iters=2)
            assert cfg == "fast"
            calls.clear()
            # second lookup: cache hit, no sweep
            cfg2 = at.autotune("toy|(4,)", ["slow", "fast"], build,
                               (jnp.ones(4),))
            assert cfg2 == "fast" and not calls
            # persistent across instances
            cache2 = at.AutotuneCache(str(tmp_path / "tune.json"))
            assert cache2.get("toy|(4,)") == 1
        finally:
            u.set_force_interpret(prev)
            GLOBAL_FLAGS.set("kernel_autotune", False)


@pytest.mark.slow
@pytest.mark.slow
def test_flash_attn_unpadded_dropout_in_kernel():
    """dropout>0 rides inside the fused kernel (position-keyed hash
    mask); training=False returns the no-dropout fused result."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(0)
    tq, h, d = 12, 2, 8
    q = paddle.to_tensor(rng.randn(tq, h, d).astype(np.float32))
    k = paddle.to_tensor(rng.randn(tq, h, d).astype(np.float32))
    v = paddle.to_tensor(rng.randn(tq, h, d).astype(np.float32))
    cu = paddle.to_tensor(np.array([0, 5, 12], np.int32))
    o0, _ = F.flash_attn_unpadded(q, k, v, cu, cu, causal=True)
    o1, _ = F.flash_attn_unpadded(q, k, v, cu, cu, causal=True,
                                  dropout=0.3, training=True)
    assert np.asarray(o1.numpy()).shape == (tq, h, d)
    assert not np.allclose(np.asarray(o0.numpy()), np.asarray(o1.numpy()))
    o2, _ = F.flash_attn_unpadded(q, k, v, cu, cu, causal=True,
                                  dropout=0.3, training=False)
    np.testing.assert_allclose(np.asarray(o0.numpy()),
                               np.asarray(o2.numpy()), atol=1e-5)
    # deterministic under the framework seed; varies across seeds
    paddle.seed(123)
    a, _ = F.flash_attn_unpadded(q, k, v, cu, cu, causal=True,
                                 dropout=0.3, training=True)
    paddle.seed(123)
    b, _ = F.flash_attn_unpadded(q, k, v, cu, cu, causal=True,
                                 dropout=0.3, training=True)
    np.testing.assert_allclose(np.asarray(a.numpy()),
                               np.asarray(b.numpy()))


class TestFlashDropout:
    """In-kernel attention dropout (VERDICT round-2 §2: 'in-kernel
    dropout RNG still missing'). The keep mask is a counter-based hash
    of absolute positions, so the forward and both backward kernels —
    and a full-matrix jnp reference — regenerate it identically."""

    def _qkv(self, B=2, S=128, H=4, KVH=2, D=64):
        import jax.numpy as jnp
        rng = np.random.RandomState(0)
        return (jnp.asarray(rng.randn(B, S, H, D), jnp.float32),
                jnp.asarray(rng.randn(B, S, KVH, D), jnp.float32),
                jnp.asarray(rng.randn(B, S, KVH, D), jnp.float32))

    def _ref(self, q, k, v, seed, rate):
        # the production full-matrix composition IS the reference — one
        # copy of the hash/GQA layout to keep bit-identical
        return _ref_attention(q, k, v, causal=True, dropout_rate=rate,
                              dropout_seed=seed)

    @pytest.mark.slow
    def test_dropout_with_segment_ids_matches_reference(self):
        """Varlen (segment-id) masking and in-kernel dropout compose —
        the actual flash_attn_unpadded training path on TPU."""
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention_pallas)
        q, k, v = self._qkv(B=2, S=128, H=2, KVH=2)
        seg = jnp.concatenate([jnp.zeros((2, 64), jnp.int32),
                               jnp.ones((2, 64), jnp.int32)], axis=1)
        seed = jnp.asarray(11, jnp.uint32)
        o_k = flash_attention_pallas(q, k, v, causal=True,
                                     segment_ids=seg, dropout_rate=0.3,
                                     dropout_seed=seed)
        o_r = _ref_attention(q, k, v, causal=True, segment_ids=seg,
                             dropout_rate=0.3, dropout_seed=seed)
        np.testing.assert_allclose(np.asarray(o_k), np.asarray(o_r),
                                   rtol=2e-5, atol=2e-5)
        # grads through the varlen+dropout kernel match the composition
        import jax as _jax

        def lk(q, k, v):
            return (flash_attention_pallas(
                q, k, v, causal=True, segment_ids=seg, dropout_rate=0.3,
                dropout_seed=seed).astype(jnp.float32) ** 2).sum()

        def lr(q, k, v):
            return (_ref_attention(
                q, k, v, causal=True, segment_ids=seg, dropout_rate=0.3,
                dropout_seed=seed).astype(jnp.float32) ** 2).sum()

        gk = _jax.grad(lk, (0, 1, 2))(q, k, v)
        gr = _jax.grad(lr, (0, 1, 2))(q, k, v)
        for a, b, name in zip(gk, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4, err_msg=name)

    @pytest.mark.slow
    def test_fwd_and_grads_match_exact_mask_reference(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention_pallas)
        q, k, v = self._qkv()
        seed = jnp.asarray(77, jnp.uint32)
        rate = 0.3
        o_k = flash_attention_pallas(q, k, v, causal=True,
                                     dropout_rate=rate, dropout_seed=seed)
        o_r = self._ref(q, k, v, seed, rate)
        np.testing.assert_allclose(np.asarray(o_k), np.asarray(o_r),
                                   rtol=2e-5, atol=2e-5)

        def lk(q, k, v):
            return (flash_attention_pallas(
                q, k, v, causal=True, dropout_rate=rate,
                dropout_seed=seed).astype(jnp.float32) ** 2).sum()

        def lr(q, k, v):
            return (self._ref(q, k, v, seed, rate)
                    .astype(jnp.float32) ** 2).sum()

        gk = jax.grad(lk, (0, 1, 2))(q, k, v)
        gr = jax.grad(lr, (0, 1, 2))(q, k, v)
        for a, b, name in zip(gk, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4, err_msg=name)

    @pytest.mark.slow
    def test_deterministic_and_mean_preserving(self):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention_pallas)
        q, k, v = self._qkv(B=1, S=128, H=2, KVH=1)
        o0 = np.asarray(flash_attention_pallas(q, k, v, causal=True))
        seed = jnp.asarray(5, jnp.uint32)
        a = np.asarray(flash_attention_pallas(
            q, k, v, causal=True, dropout_rate=0.3, dropout_seed=seed))
        b = np.asarray(flash_attention_pallas(
            q, k, v, causal=True, dropout_rate=0.3, dropout_seed=seed))
        np.testing.assert_array_equal(a, b)
        acc = np.zeros_like(o0)
        N = 24
        for i in range(N):
            acc += np.asarray(flash_attention_pallas(
                q, k, v, causal=True, dropout_rate=0.3,
                dropout_seed=jnp.asarray(100 + i, jnp.uint32)))
        err = np.abs(acc / N - o0).mean() / (np.abs(o0).mean() + 1e-9)
        assert err < 0.15, err


def test_autotune_cache_key_matches_tuned_blocks():
    """bench's flash_tune reports winners via autotune_cache_key; it must
    stay byte-identical to the key _tuned_blocks writes, or the sweep
    silently reports None winners after a key-format change."""
    import jax
    import jax.numpy as jnp
    from unittest import mock
    from paddle_tpu.ops.pallas import flash_attention as F

    q = jnp.zeros((8, 2048, 128), jnp.bfloat16)   # folded [b*h, s, d]
    k = jnp.zeros((4, 2048, 128), jnp.bfloat16)
    seen = {}

    def fake_get(ck):
        seen["ck"] = ck
        return None

    with mock.patch.object(F, "autotune_cache_key",
                           wraps=F.autotune_cache_key):
        with mock.patch.object(
                __import__("paddle_tpu.ops.pallas.autotune",
                           fromlist=["_cache"])._cache, "get",
                side_effect=fake_get):
            from paddle_tpu.core.flags import GLOBAL_FLAGS
            prev = GLOBAL_FLAGS.get("kernel_autotune")
            GLOBAL_FLAGS.set("kernel_autotune", True)
            try:
                # traced call -> reads the cache via the internal key
                jax.eval_shape(
                    lambda q, k: F._tuned_blocks(
                        q, k, k, None, None, None, 1.0, True,
                        (8, 4, 2048, 2048, 128, 1.0, True)) or (1, 1),
                    q, k)
            finally:
                GLOBAL_FLAGS.set("kernel_autotune", prev)
    expect = F.autotune_cache_key(8, 2048, 2048, 4, 128, True,
                                  "bfloat16")
    assert seen.get("ck") == expect, (seen.get("ck"), expect)
