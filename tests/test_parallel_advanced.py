"""Pipeline / MoE / ring-attention tests (8-device CPU mesh)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.distributed as dist


@pytest.fixture
def pp_hcg():
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 4, "sharding_degree": 1,
                               "sep_degree": 1}
    strategy.pipeline_configs = {"accumulate_steps": 4}
    h = fleet.init(is_collective=True, strategy=strategy)
    yield h
    dist.set_hybrid_communicate_group(None)


class TestPipeline:
    def _descs(self):
        from paddle_tpu.distributed.fleet.pipeline_parallel import LayerDesc
        return [LayerDesc(nn.Linear, 8, 8) for _ in range(8)]

    def test_segmentation(self):
        from paddle_tpu.distributed.fleet.pipeline_parallel import \
            PipelineLayer
        pl_ = PipelineLayer(self._descs(), num_stages=4,
                            loss_fn=nn.MSELoss())
        assert pl_.segment_parts == [0, 2, 4, 6, 8]
        assert len(pl_.get_stage_layers(0)) == 2

    @pytest.mark.slow
    def test_pipeline_matches_plain(self, pp_hcg):
        """PP training must produce the same params as the plain model."""
        from paddle_tpu.distributed.fleet.pipeline_parallel import \
            PipelineLayer, PipelineParallel
        paddle.seed(5)
        plain = nn.Sequential(*[nn.Linear(8, 8) for _ in range(4)])
        paddle.seed(5)
        from paddle_tpu.distributed.fleet.pipeline_parallel import LayerDesc
        pipe_layer = PipelineLayer(
            [LayerDesc(nn.Linear, 8, 8) for _ in range(4)],
            num_stages=4, loss_fn=nn.MSELoss())
        # same init
        pipe_layer.set_state_dict(
            {k.replace("_all.", ""): v
             for k, v in plain.state_dict().items()})
        for (n1, p1), (n2, p2) in zip(
                sorted(plain.state_dict().items()),
                sorted(pipe_layer.state_dict().items())):
            p2._replace_value(jax.device_put(
                jnp.array(p1._value, copy=True), p2._value.sharding))

        x = paddle.randn([8, 8])
        y = paddle.randn([8, 8])
        opt_a = paddle.optimizer.SGD(0.1, parameters=plain.parameters(),
                                     multi_precision=False)
        opt_b = paddle.optimizer.SGD(0.1,
                                     parameters=pipe_layer.parameters(),
                                     multi_precision=False)
        # plain: full-batch step
        loss_a = F.mse_loss(plain(x), y)
        loss_a.backward()
        opt_a.step()
        # pipeline: 4 micro-batches, 1F1B
        engine = PipelineParallel(pipe_layer, pp_hcg, accumulate_steps=4)
        loss_b = engine.train_batch((x, y), opt_b)
        w_a = plain[0].weight.numpy()
        w_b = list(pipe_layer.parameters())[0].numpy()
        np.testing.assert_allclose(w_a, w_b, rtol=1e-4, atol=1e-5)

    def test_shared_layer_desc(self):
        from paddle_tpu.distributed.fleet.pipeline_parallel import \
            PipelineLayer, LayerDesc, SharedLayerDesc
        descs = [
            SharedLayerDesc("emb", nn.Linear, None, "weight", 8, 8),
            LayerDesc(nn.Tanh),
            SharedLayerDesc("emb", nn.Linear, None, "weight", 8, 8),
            LayerDesc(nn.Tanh),
        ]
        pl_ = PipelineLayer(descs, num_stages=2, loss_fn=nn.MSELoss())
        params = pl_.parameters()
        # shared: only one weight+bias registered
        assert len(params) == 2

    def test_seg_method_by_layer(self):
        from paddle_tpu.distributed.fleet.pipeline_parallel import \
            PipelineLayer, LayerDesc
        descs = ([LayerDesc(nn.Linear, 4, 4)] +
                 [LayerDesc(nn.Tanh) for _ in range(3)] +
                 [LayerDesc(nn.Linear, 4, 4) for _ in range(3)])
        pl_ = PipelineLayer(descs, num_stages=2, loss_fn=nn.MSELoss(),
                            seg_method="layer:Linear")
        # 4 Linears total → 2 per stage
        n_linear_s0 = sum(1 for l in pl_.get_stage_layers(0)
                          if isinstance(l, nn.Linear))
        assert n_linear_s0 == 2


class TestMoE:
    @pytest.mark.slow
    def test_moe_forward_backward(self):
        from paddle_tpu.distributed.fleet.moe import MoELayer
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=4,
                       gate="gshard")
        x = paddle.randn([2, 8, 16])
        x.stop_gradient = False
        out = moe(x)
        assert out.shape == [2, 8, 16]
        (out.sum() + moe.aux_loss * 0.01).backward()
        assert moe.w_in.grad is not None
        assert moe.gate.weight.grad is not None

    @pytest.mark.slow
    def test_switch_gate_top1(self):
        from paddle_tpu.distributed.fleet.moe import MoELayer
        moe = MoELayer(d_model=8, d_hidden=16, num_experts=2,
                       gate="switch")
        out = moe(paddle.randn([4, 8]))
        assert out.shape == [4, 8]

    @pytest.mark.slow
    def test_capacity_drops_tokens(self):
        from paddle_tpu.distributed.fleet.moe import moe_dispatch_combine
        # all tokens to one expert with tiny capacity: most get dropped
        T, D, E = 32, 4, 4
        x = jnp.ones((T, D))
        logits = jnp.tile(jnp.asarray([[10.0, 0.0, 0.0, 0.0]]), (T, 1))

        def expert_fn(tok):
            return tok * 2.0

        out, aux = moe_dispatch_combine(x, logits, expert_fn, top_k=1,
                                        capacity_factor=0.5)
        kept = np.count_nonzero(np.asarray(out).sum(-1))
        assert kept < T  # capacity limit enforced

    @pytest.mark.slow
    def test_moe_expert_sharding(self):
        from paddle_tpu.distributed import fleet
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                                   "pp_degree": 1, "sharding_degree": 1,
                                   "sep_degree": 1}
        fleet.init(strategy=strategy)
        try:
            from paddle_tpu.distributed.fleet.moe import MoELayer
            moe = MoELayer(d_model=8, d_hidden=16, num_experts=8,
                           ep_axis="mp")
            assert "mp" in str(moe.w_in._value.sharding.spec)
            out = moe(paddle.randn([4, 8]))
            assert out.shape == [4, 8]
        finally:
            dist.set_hybrid_communicate_group(None)


class TestRingAttention:
    def _mesh(self):
        return Mesh(np.array(jax.devices()[:4]), axis_names=("sp",))

    @pytest.mark.slow
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal):
        from paddle_tpu.ops.ring_attention import ring_attention
        from paddle_tpu.ops.flash_attention import _ref_attention
        rng = np.random.RandomState(0)
        b, s, h, d = 2, 64, 2, 16
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        out = ring_attention(q, k, v, self._mesh(), causal=causal)
        ref = _ref_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_ulysses_matches_full(self):
        from paddle_tpu.ops.ring_attention import ulysses_attention
        from paddle_tpu.ops.flash_attention import _ref_attention
        rng = np.random.RandomState(1)
        b, s, h, d = 1, 64, 4, 16  # h divisible by sp=4
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        out = ulysses_attention(q, k, v, self._mesh(), causal=True)
        ref = _ref_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.slow
    def test_ring_grad(self):
        from paddle_tpu.ops.ring_attention import ring_attention
        from paddle_tpu.ops.flash_attention import _ref_attention
        rng = np.random.RandomState(2)
        b, s, h, d = 1, 32, 1, 8
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        mesh = self._mesh()
        g1 = jax.grad(lambda q: jnp.sum(
            ring_attention(q, q, q, mesh, causal=True) ** 2))(q)
        g2 = jax.grad(lambda q: jnp.sum(
            _ref_attention(q, q, q, causal=True) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   atol=1e-4, rtol=1e-3)


@pytest.fixture
def mp_hcg():
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 4,
                               "pp_degree": 1, "sharding_degree": 2,
                               "sep_degree": 1}
    h = fleet.init(is_collective=True, strategy=strategy)
    yield h
    dist.set_hybrid_communicate_group(None)


class TestSequenceParallelLayers:
    """Explicit Megatron-SP API (reference:
    fleet/utils/sequence_parallel_utils.py:429,564)."""

    def test_column_row_sp_roundtrip(self, mp_hcg):
        import numpy as np
        from paddle_tpu.distributed.fleet.sequence_parallel_utils import (
            ColumnSequenceParallelLinear, RowSequenceParallelLinear,
            ScatterOp, GatherOp)
        import paddle_tpu as paddle
        paddle.seed(0)
        col = ColumnSequenceParallelLinear(16, 32, has_bias=True)
        row = RowSequenceParallelLinear(32, 16, has_bias=True)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(8, 2, 16).astype(np.float32))
        h = ScatterOp.apply(x)
        h = col(h)
        h = row(h)
        out = GatherOp.apply(h)
        # numerics == plain two-layer MLP with the same weights
        ref = (np.asarray(x._value) @ np.asarray(col.weight._value)
               + np.asarray(col.bias._value))
        ref = ref @ np.asarray(row.weight._value) + \
            np.asarray(row.bias._value)
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=1e-4, atol=1e-5)


# -- fused_moe (reference: incubate/nn/functional/fused_moe.py) -------------
class TestFusedMoe:
    @pytest.mark.slow
    def test_matches_dense_top2_reference(self):
        from paddle_tpu.incubate.nn.functional import fused_moe

        rng = np.random.RandomState(0)
        B, S, D, E, Fd = 2, 8, 16, 4, 32
        x = paddle.to_tensor(rng.randn(B, S, D).astype(np.float32))
        gw = paddle.to_tensor((rng.randn(D, E) * 0.1).astype(np.float32))
        w1 = paddle.to_tensor(
            (rng.randn(E, D, 2 * Fd) * 0.1).astype(np.float32))
        w2 = paddle.to_tensor(
            (rng.randn(E, Fd, D) * 0.1).astype(np.float32))
        b1 = paddle.to_tensor(
            (rng.randn(E, 1, 2 * Fd) * 0.1).astype(np.float32))
        b2 = paddle.to_tensor(
            (rng.randn(E, 1, D) * 0.1).astype(np.float32))
        out = np.asarray(fused_moe(
            x, gw, w1, w2, ffn1_bias=b1, ffn2_bias=b2, moe_topk=2,
            capacity_factor=float(E)).numpy())  # exact: no drops

        xv = np.asarray(x.numpy()).reshape(-1, D)
        logits = xv @ np.asarray(gw.numpy())
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        topk = np.argsort(-probs, axis=-1)[:, :2]
        w1n, w2n = np.asarray(w1.numpy()), np.asarray(w2.numpy())
        b1n, b2n = np.asarray(b1.numpy()), np.asarray(b2.numpy())

        def silu(v):
            return v / (1 + np.exp(-v))

        ref = np.zeros_like(xv)
        for t in range(xv.shape[0]):
            g = probs[t, topk[t]]
            g = g / g.sum()
            for kk in range(2):
                e = topk[t, kk]
                h = xv[t] @ w1n[e] + b1n[e, 0]
                a, gg = np.split(h, 2)
                ref[t] += g[kk] * ((silu(a) * gg) @ w2n[e] + b2n[e, 0])
        np.testing.assert_allclose(out.reshape(-1, D), ref, atol=1e-4)

    def test_gelu_variant_and_quant_guard(self):
        from paddle_tpu.incubate.nn.functional import fused_moe

        rng = np.random.RandomState(1)
        x = paddle.to_tensor(rng.randn(1, 4, 8).astype(np.float32))
        gw = paddle.to_tensor(rng.randn(8, 2).astype(np.float32))
        w1 = paddle.to_tensor(rng.randn(2, 8, 16).astype(np.float32))
        w2 = paddle.to_tensor(rng.randn(2, 16, 8).astype(np.float32))
        out = fused_moe(x, gw, w1, w2, moe_topk=1, capacity_factor=2.0)
        assert np.asarray(out.numpy()).shape == (1, 4, 8)
        with pytest.raises(NotImplementedError):
            fused_moe(x, gw, w1, w2, quant_method="weight_only_int8")

    def test_capacity_drop_warns_once(self, monkeypatch):
        """ADVICE round-2: silent token drops past expert capacity must
        warn (the reference grouped GEMM computes all routed tokens)."""
        import warnings
        from paddle_tpu.distributed.fleet import moe as moe_mod
        from paddle_tpu.incubate.nn.functional import fused_moe

        monkeypatch.setattr(moe_mod, "_CAPACITY_DROP_WARNED", False)
        rng = np.random.RandomState(2)
        # All tokens route to one expert; capacity_factor keeps only a few
        x = paddle.to_tensor(np.ones((1, 32, 8), np.float32))
        gw = np.zeros((8, 4), np.float32)
        gw[:, 0] = 1.0  # expert 0 dominates every token
        gw = paddle.to_tensor(gw)
        w1 = paddle.to_tensor(rng.randn(4, 8, 16).astype(np.float32))
        w2 = paddle.to_tensor(rng.randn(4, 16, 8).astype(np.float32))
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            out = fused_moe(x, gw, w1, w2, moe_topk=1,
                            capacity_factor=0.25)
            np.asarray(out.numpy())
            jax.effects_barrier()  # debug callbacks are async-delivered
        msgs = [str(w.message) for w in rec if "dropped" in str(w.message)]
        assert len(msgs) == 1
