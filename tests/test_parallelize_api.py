"""Plan-based parallelize API + PS datasets + comm compat (reference:
distributed/auto_parallel/intermediate/*, fleet/dataset, parallel.py)."""
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist


@pytest.fixture
def mesh2d():
    m = dist.ProcessMesh(np.arange(8).reshape(4, 2),
                         dim_names=["dp", "mp"])
    dist.auto_parallel.api.set_mesh(m)
    yield m
    dist.auto_parallel.api.set_mesh(None)


def _specs(t):
    sh = t._value.sharding
    return tuple(sh.spec) if hasattr(sh, "spec") else None


class TestParallelizePlans:
    def test_col_row_plans_shard_weights(self, mesh2d):
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                            nn.Linear(32, 16))
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(4, 16).astype(np.float32))
        ref = net(x).numpy()
        model, _ = dist.parallelize(
            net, mesh=mesh2d,
            config={"mp_config": {"parallelize_plan": {
                "0": dist.ColWiseParallel(),
                "2": dist.RowWiseParallel(),
            }}})
        # weight [in, out]: col plan shards OUT dim on mp, row plan IN
        assert _specs(model[0].weight)[1] == "mp"
        assert _specs(model[2].weight)[0] == "mp"
        # forward math unchanged (GSPMD inserts the collectives)
        np.testing.assert_allclose(model(x).numpy(), ref, atol=1e-5)

    def test_sharding_level3_shards_params(self, mesh2d):
        net = nn.Linear(8, 32)
        model, _ = dist.parallelize(
            net, mesh=mesh2d, config={"dp_config": {"sharding_level": 3}})
        assert _specs(model.weight)[0] == "dp"

    def test_prepare_layer_output_hook(self, mesh2d):
        net = nn.Sequential(nn.Linear(4, 4))
        seen = []

        def hook(out):
            seen.append(True)
            return out

        model, _ = dist.parallelize(
            net, mesh=mesh2d,
            config={"mp_config": {"parallelize_plan": {
                "0": dist.PrepareLayerOutput(hook)}}})
        model(paddle.to_tensor(np.zeros((2, 4), np.float32)))
        assert seen

    def test_sequence_parallel_enable_forward_parity(self, mesh2d):
        net = nn.Linear(16, 16)
        x = paddle.to_tensor(np.random.RandomState(1)
                             .randn(2, 8, 16).astype(np.float32))
        ref = net(x).numpy()
        model, _ = dist.parallelize(
            net, mesh=mesh2d,
            config={"mp_config": {"parallelize_plan": {
                "": dist.SequenceParallelEnable()}}})
        np.testing.assert_allclose(model(x).numpy(), ref, atol=1e-5)

    def test_pp_config_points_at_pipeline_engine(self, mesh2d):
        with pytest.raises(NotImplementedError, match="Compiled1F1B"):
            dist.parallelize(nn.Linear(4, 4), mesh=mesh2d,
                             config={"pp_config": {"split_spec": "x"}})

    def test_to_distributed_auto_plans(self, mesh2d):
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                            nn.Linear(32, 16))
        opt = paddle.optimizer.AdamW(1e-3, parameters=net.parameters())
        model, opt2, _ = dist.to_distributed(net, opt, None, 8)
        sharded = [
            _specs(p) for _n, p in model.named_parameters()
            if len(p.shape) == 2 and _specs(p)
            and any(s == "mp" for s in _specs(p))]
        assert sharded, "no weight got an mp placement"

    def test_local_layer_places_outputs(self, mesh2d):
        class Doubler(dist.LocalLayer):
            def forward(self, x):
                return x * 2

        lay = Doubler(out_dist_attrs=[
            (mesh2d, [dist.Shard(0), dist.Replicate()])])
        x = paddle.to_tensor(np.ones((8, 4), np.float32))
        out = lay(x)
        np.testing.assert_allclose(out.numpy(), 2.0)
        assert _specs(out)[0] == "dp"

    def test_misc_small_apis(self, mesh2d):
        t = dist.dtensor_from_fn(
            lambda: paddle.to_tensor(np.ones((8, 4), np.float32)),
            mesh2d, [dist.Shard(0), dist.Replicate()])
        assert _specs(t)[0] == "dp"
        st = dist.Strategy({"sharding": {"stage": 2}})
        assert st.sharding.stage == 2
        assert dist.ShardingStage3().level == 3
        assert dist.SplitPoint.END.name == "END"
        assert dist.ReduceType.kRedSum is not None
        attr = dist.DistAttr(mesh2d, ["x", None])
        assert "x" in repr(attr)
        from paddle_tpu.amp import GradScaler
        sc = GradScaler(init_loss_scaling=8.0)
        assert dist.shard_scaler(sc) is sc


class TestCommCompat:
    def test_backend_lifecycle(self):
        assert dist.is_available()
        assert dist.get_backend() in ("gloo", "xla")
        dist.destroy_process_group()   # no-op without init

    def test_scatter_object_list_single(self):
        out = []
        dist.scatter_object_list(out, [{"a": 1}], src=0)
        assert out == [{"a": 1}]

    def test_gloo_group_barrier_two_ranks(self):
        import socket
        import threading
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        ep = f"127.0.0.1:{port}"
        errs = []

        def rank1():
            try:
                import time
                time.sleep(0.3)
                from paddle_tpu.distributed import comm_compat as cc
                # rank 1 uses its own module state? same process: use a
                # raw store client + matching barrier key instead
                from paddle_tpu.distributed.store import TCPStore
                st = TCPStore("127.0.0.1", port)
                st.set("gloo/rank/1", "up")
                st.barrier("gloo/barrier/1", 2)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        th = threading.Thread(target=rank1)
        th.start()
        dist.gloo_init_parallel_env(0, 2, ep)
        dist.gloo_barrier()
        th.join(timeout=30)
        dist.gloo_release()
        assert not errs and not th.is_alive()


class TestPSDatasets:
    def _write_slot_file(self, path, n=10):
        from paddle_tpu.distributed.fleet import MultiSlotDataGenerator

        class Gen(MultiSlotDataGenerator):
            def generate_sample(self, line):
                def it():
                    i = int(line)
                    yield [("ids", [i, i + 1, i + 2]),
                           ("label", [i % 2])]
                return it

        g = Gen()
        import io
        buf = io.StringIO()
        g.set_batch(4)
        g.run_from_stdin(stdin=[str(i) for i in range(n)], out=buf)
        path.write_text(buf.getvalue())
        return buf.getvalue()

    def test_generator_format_and_inmemory_roundtrip(self, tmp_path):
        f = tmp_path / "part-0"
        text = self._write_slot_file(f)
        assert text.splitlines()[0] == "3 0 1 2 1 0"
        ds = dist.InMemoryDataset()
        ds.init(batch_size=4, use_var=["ids", "label"])
        ds.set_filelist([str(f)])
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 10
        ds.local_shuffle(seed=0)
        batches = list(ds)
        assert batches[0]["ids"].shape == (4, 3)
        assert batches[0]["label"].dtype == np.int64
        total = sum(b["label"].shape[0] for b in batches)
        assert total == 10
        ds.release_memory()
        assert ds.get_memory_data_size() == 0

    def test_queue_dataset_streams(self, tmp_path):
        f = tmp_path / "part-0"
        self._write_slot_file(f, n=6)
        ds = dist.QueueDataset()
        ds.init(batch_size=2, use_var=["ids", "label"])
        ds.set_filelist([str(f)])
        assert sum(1 for _ in ds) == 3
        ds.set_show_click_entry(dist.ShowClickEntry("show", "click"))
        with pytest.raises(ValueError):
            dist.ShowClickEntry("", "click")


def _spawn_worker(out_dir):
    import os as _os
    rank = _os.environ["PADDLE_TRAINER_ID"]
    with open(f"{out_dir}/spawned_{rank}", "w") as f:
        f.write(_os.environ["PADDLE_TRAINERS_NUM"])


@pytest.mark.slow
def test_spawn_runs_workers_with_env(tmp_path):
    dist.spawn(_spawn_worker, args=(str(tmp_path),), nprocs=2)
    for r in range(2):
        assert (tmp_path / f"spawned_{r}").read_text() == "2"
