"""Pipeline schedule tests: interleaved VPP chunk placement + zero-bubble
dW/dX split (reference: pipeline_parallel.py:1308 interleave,
pipeline_zero_bubble.py ZB-H1)."""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.fleet.pipeline_parallel import (
    PipelineLayer, PipelineParallel, PipelineParallelWithInterleave,
    ZeroBubblePipelineParallel, LayerDesc)
from paddle_tpu.distributed.fleet.zero_bubble import (WeightGradStore,
                                                      zb_linear)


def _mse(out, label):
    return F.mse_loss(out, label)


def _descs(n=8, width=6):
    return [LayerDesc(nn.Linear, width, width) for _ in range(n)]


# -- zero-bubble dW/dX split ------------------------------------------------
def test_zb_linear_matches_plain_linear_grads():
    rng = np.random.RandomState(0)
    w = rng.randn(4, 3).astype(np.float32)
    b = rng.randn(3).astype(np.float32)
    xv = rng.randn(5, 4).astype(np.float32)

    # plain reference grads
    lin = nn.Linear(4, 3)
    lin.weight.set_value(paddle.to_tensor(w))
    lin.bias.set_value(paddle.to_tensor(b))
    x1 = paddle.to_tensor(xv)
    x1.stop_gradient = False
    out_ref = lin(x1)
    out_ref.sum().backward()
    ref_dx = x1.grad.numpy()
    ref_dw = lin.weight.grad.numpy()
    ref_db = lin.bias.grad.numpy()

    # zb path: dX immediately, dW/db only after flush
    lin2 = nn.Linear(4, 3)
    lin2.weight.set_value(paddle.to_tensor(w))
    lin2.bias.set_value(paddle.to_tensor(b))
    x2 = paddle.to_tensor(xv)
    x2.stop_gradient = False
    store = WeightGradStore()
    with store:
        out = lin2(x2)     # F.linear routes through zb_linear
    out.sum().backward()
    np.testing.assert_allclose(x2.grad.numpy(), ref_dx, rtol=1e-5)
    assert lin2.weight.grad is None      # dW deferred
    assert len(store) == 1
    store.flush()
    np.testing.assert_allclose(lin2.weight.grad.numpy(), ref_dw,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(lin2.bias.grad.numpy(), ref_db, rtol=1e-5)
    assert len(store) == 0


@pytest.mark.slow
def test_zb_pipeline_grads_match_plain_pipeline():
    paddle.seed(7)
    pl1 = PipelineLayer(_descs(), num_stages=2, loss_fn=_mse)
    paddle.seed(7)
    pl2 = PipelineLayer(_descs(), num_stages=2, loss_fn=_mse)
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 6)
                         .astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randn(8, 6)
                         .astype(np.float32))

    plain = PipelineParallel(pl1, accumulate_steps=4)
    zb = ZeroBubblePipelineParallel(pl2, accumulate_steps=4)
    l1 = plain.forward_backward_pipeline((x, y))
    l2 = zb.forward_backward_pipeline((x, y))
    np.testing.assert_allclose(float(l1.numpy()), float(l2.numpy()),
                               rtol=1e-5)
    g1 = [p.grad.numpy() for p in plain.parameters()]
    g2 = [p.grad.numpy() for p in zb.parameters()]
    assert len(g1) == len(g2)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_zb_training_step_reduces_loss():
    paddle.seed(3)
    pl = PipelineLayer(_descs(4), num_stages=2, loss_fn=_mse)
    engine = ZeroBubblePipelineParallel(pl, accumulate_steps=2)
    opt = paddle.optimizer.SGD(0.05, parameters=engine.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 6)
                         .astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randn(4, 6)
                         .astype(np.float32))
    losses = [float(engine.train_batch((x, y), opt).numpy())
              for _ in range(10)]
    assert losses[-1] < losses[0]


# -- interleaved VPP --------------------------------------------------------
def test_vpp_chunk_round_robin_placement():
    pl = PipelineLayer(_descs(8), num_stages=2, loss_fn=_mse,
                       num_virtual_pipeline_stages=2)
    assert pl._num_chunks == 4
    # 8 layers → 4 chunks of 2; chunk c on stage c % 2
    assert pl.segment_parts == [0, 2, 4, 6, 8]
    assert [pl.chunk_of(i) for i in range(8)] == [0, 0, 1, 1, 2, 2, 3, 3]
    assert [pl.stage_of(i) for i in range(8)] == [0, 0, 1, 1, 0, 0, 1, 1]
    # stage 0 hosts chunks 0 and 2
    assert len(pl.get_stage_layers(0)) == 4
    assert len(pl.get_chunk_layers(1)) == 2


def test_vpp_forward_matches_sequential():
    paddle.seed(11)
    descs = _descs(6)
    pl = PipelineLayer(descs, num_stages=2, loss_fn=_mse,
                       num_virtual_pipeline_stages=3)
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 6)
                         .astype(np.float32))
    out = pl(x)
    ref = x
    for l in pl.run_function:
        ref = l(ref)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-6)


def test_vpp_engine_trains():
    paddle.seed(5)
    pl = PipelineLayer(_descs(8), num_stages=2, loss_fn=_mse,
                       num_virtual_pipeline_stages=2)
    engine = PipelineParallelWithInterleave(pl, accumulate_steps=2)
    opt = paddle.optimizer.SGD(0.05, parameters=engine.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 6)
                         .astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randn(4, 6)
                         .astype(np.float32))
    losses = [float(engine.train_batch((x, y), opt).numpy())
              for _ in range(10)]
    assert losses[-1] < losses[0]


def test_zb_linear_input_stop_gradient_still_defers_dw():
    lin = nn.Linear(3, 2)
    x = paddle.to_tensor(np.ones((2, 3), np.float32))   # stop_gradient
    store = WeightGradStore()
    with store:
        out = lin(x)
    out.sum().backward()
    assert lin.weight.grad is None
    store.flush()
    assert lin.weight.grad is not None
    np.testing.assert_allclose(lin.weight.grad.numpy(),
                               np.full((3, 2), 2.0), rtol=1e-6)


# -- compiled pipeline (shard_map + scan + ppermute; SURVEY §7 hard part a) --
class TestCompiledPipeline:
    def _setup(self, S=4, M=8, D=16, mb=4, seed=0):
        import jax
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.pp_compiled import CompiledPipeline
        mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
        rng = np.random.RandomState(seed)
        W = jnp.asarray(rng.randn(S, 2, D, D) * 0.1, jnp.float32)
        B = jnp.asarray(rng.randn(S, 2, D) * 0.1, jnp.float32)

        def stage_fn(p, x):
            w, b = p
            for i in range(2):
                x = jnp.tanh(x @ w[i] + b[i])
            return x

        pipe = CompiledPipeline(stage_fn, mesh, num_microbatches=M)
        x = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        y = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        return pipe, stage_fn, mesh, (W, B), x, y, S

    @pytest.mark.slow
    def test_fwd_bwd_matches_sequential(self):
        import jax
        pipe, stage_fn, mesh, params, x, y_tgt, S = self._setup()

        def loss_pipe(params, x, y_tgt):
            return jnp.mean((pipe(params, x) - y_tgt) ** 2)

        def loss_seq(params, x, y_tgt):
            W, B = params

            def fwd(v):
                for s in range(S):
                    v = stage_fn((W[s], B[s]), v)
                return v
            return jnp.mean((jax.vmap(fwd)(x) - y_tgt) ** 2)

        with mesh:
            lp, gp = jax.jit(jax.value_and_grad(loss_pipe))(params, x,
                                                            y_tgt)
        ls, gs = jax.jit(jax.value_and_grad(loss_seq))(params, x, y_tgt)
        assert abs(float(lp) - float(ls)) < 1e-6
        for a, b in zip(jax.tree_util.tree_leaves(gp),
                        jax.tree_util.tree_leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    @pytest.mark.slow
    def test_trains(self):
        import jax
        pipe, _, mesh, params, x, y_tgt, _ = self._setup()

        @jax.jit
        def step(params, x, y_tgt):
            def loss(p):
                return jnp.mean((pipe(p, x) - y_tgt) ** 2)
            l, g = jax.value_and_grad(loss)(params)
            return l, jax.tree_util.tree_map(lambda p, gg: p - 0.5 * gg,
                                             params, g)

        with mesh:
            losses = []
            for _ in range(5):
                l, params = step(params, x, y_tgt)
                losses.append(float(l))
        assert losses[-1] < losses[0]

    def test_1f1b_matches_sequential(self):
        """Compiled 1F1B (manual vjp ticks, no AD through the scan) must
        produce the same loss/grads as the jitted sequential model."""
        import jax
        from paddle_tpu.distributed.fleet.pp_compiled import Compiled1F1B
        pipe, stage_fn, mesh, params, x, y_tgt, S = self._setup()

        def loss_fn(y, label):
            return jnp.mean((y - label) ** 2)

        fifo = Compiled1F1B(stage_fn, loss_fn, mesh, num_microbatches=8)
        with mesh:
            lp, gp = jax.jit(fifo.loss_and_grads)(params, x, y_tgt)

        def loss_seq(params, x, y_tgt):
            W, B = params

            def fwd(v):
                for s in range(S):
                    v = stage_fn((W[s], B[s]), v)
                return v
            per_mb = jax.vmap(
                lambda xv, yv: loss_fn(fwd(xv), yv))(x, y_tgt)
            return jnp.mean(per_mb)

        ls, gs = jax.jit(jax.value_and_grad(loss_seq))(params, x, y_tgt)
        assert abs(float(lp) - float(ls)) < 1e-6
        for a, b in zip(jax.tree_util.tree_leaves(gp),
                        jax.tree_util.tree_leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_1f1b_split_dw_matches(self):
        """ZB dW/dX split (deferred W slot) computes identical grads."""
        import jax
        from paddle_tpu.distributed.fleet.pp_compiled import Compiled1F1B
        pipe, stage_fn, mesh, params, x, y_tgt, S = self._setup()

        def loss_fn(y, label):
            return jnp.mean((y - label) ** 2)

        plain = Compiled1F1B(stage_fn, loss_fn, mesh, num_microbatches=8)
        zb = Compiled1F1B(stage_fn, loss_fn, mesh, num_microbatches=8,
                          split_dw=True)
        with mesh:
            l0, g0 = jax.jit(plain.loss_and_grads)(params, x, y_tgt)
            l1, g1 = jax.jit(zb.loss_and_grads)(params, x, y_tgt)
        assert abs(float(l0) - float(l1)) < 1e-6
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)

    def test_1f1b_fewer_microbatches_than_stages(self):
        """M < S (bubble-heavy edge): the masked schedule must still be
        exact vs sequential."""
        import jax
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.pp_compiled import Compiled1F1B
        S, M, D, mb = 4, 2, 8, 2
        mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
        rng = np.random.RandomState(0)
        W = jnp.asarray(rng.randn(S, 2, D, D) * 0.1, jnp.float32)
        B = jnp.asarray(rng.randn(S, 2, D) * 0.1, jnp.float32)

        def stage_fn(p, x):
            w, b = p
            for i in range(2):
                x = jnp.tanh(x @ w[i] + b[i])
            return x

        def loss_fn(y, label):
            return jnp.mean((y - label) ** 2)

        x = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        y = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        pipe = Compiled1F1B(stage_fn, loss_fn, mesh, num_microbatches=M,
                            split_dw=True)
        with mesh:
            lp, gp = jax.jit(pipe.loss_and_grads)((W, B), x, y)

        def loss_seq(params, x, y):
            Wp, Bp = params

            def fwd(v):
                for s in range(S):
                    v = stage_fn((Wp[s], Bp[s]), v)
                return v
            return jnp.mean(jax.vmap(
                lambda a, b: loss_fn(fwd(a), b))(x, y))

        ls, gs = jax.jit(jax.value_and_grad(loss_seq))((W, B), x, y)
        assert abs(float(lp) - float(ls)) < 1e-6
        for a, b in zip(jax.tree_util.tree_leaves(gp),
                        jax.tree_util.tree_leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_interleaved_hybrid_pp_dp_matches_sequential(self):
        """VPP on a pp2 x dp2 mesh with the batch dim dp-sharded must
        equal the unsharded sequential model (same contract as the 1F1B
        data_axis)."""
        import jax
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.pp_compiled import (
            CompiledInterleaved)
        S, DP, V, M, mb, D = 2, 2, 2, 8, 4, 12
        L = V * S
        mesh = Mesh(np.array(jax.devices()[:S * DP]).reshape(S, DP),
                    ("pp", "dp"))
        rng = np.random.RandomState(42)
        W = jnp.asarray(rng.randn(S, V, D, D) * 0.1, jnp.float32)
        B = jnp.asarray(rng.randn(S, V, D) * 0.1, jnp.float32)

        def chunk_fn(p, x):
            w, b = p
            return jnp.tanh(x @ w + b)

        def loss_fn(y, label):
            return jnp.mean((y - label) ** 2)

        x = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        y = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        vpp = CompiledInterleaved(chunk_fn, loss_fn, mesh,
                                  num_microbatches=M, num_chunks=V,
                                  split_dw=True, data_axis="dp")
        with mesh:
            lp, gp = jax.jit(vpp.loss_and_grads)((W, B), x, y)

        def loss_seq(params, x, y):
            Wp, Bp = params

            def fwd(v):
                for c in range(L):
                    v = chunk_fn((Wp[c % S, c // S],
                                  Bp[c % S, c // S]), v)
                return v
            return jnp.mean(jax.vmap(
                lambda a, b: loss_fn(fwd(a), b))(x, y))

        ls, gs = jax.jit(jax.value_and_grad(loss_seq))((W, B), x, y)
        assert abs(float(lp) - float(ls)) < 1e-6
        for a, b in zip(jax.tree_util.tree_leaves(gp),
                        jax.tree_util.tree_leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_1f1b_hybrid_pp_dp_matches_sequential(self):
        """pp2 x dp2 mesh: batch dim sharded over dp, grads/loss averaged
        over dp in-graph — must equal the unsharded sequential model."""
        import jax
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.pp_compiled import Compiled1F1B
        S, DP, M, mb, D = 2, 2, 8, 4, 16
        mesh = Mesh(np.array(jax.devices()[:S * DP]).reshape(S, DP),
                    ("pp", "dp"))
        rng = np.random.RandomState(3)
        W = jnp.asarray(rng.randn(S, 2, D, D) * 0.1, jnp.float32)
        B = jnp.asarray(rng.randn(S, 2, D) * 0.1, jnp.float32)

        def stage_fn(p, x):
            w, b = p
            for i in range(2):
                x = jnp.tanh(x @ w[i] + b[i])
            return x

        def loss_fn(y, label):
            return jnp.mean((y - label) ** 2)

        x = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        y_tgt = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        pipe = Compiled1F1B(stage_fn, loss_fn, mesh, num_microbatches=M,
                            split_dw=True, data_axis="dp")
        with mesh:
            lp, gp = jax.jit(pipe.loss_and_grads)((W, B), x, y_tgt)

        def loss_seq(params, x, y_tgt):
            Ws, Bs = params

            def fwd(v):
                for s in range(S):
                    v = stage_fn((Ws[s], Bs[s]), v)
                return v
            per_mb = jax.vmap(lambda xv, yv: loss_fn(fwd(xv), yv))(x, y_tgt)
            return jnp.mean(per_mb)

        ls, gs = jax.jit(jax.value_and_grad(loss_seq))((W, B), x, y_tgt)
        assert abs(float(lp) - float(ls)) < 1e-6
        for a, b in zip(jax.tree_util.tree_leaves(gp),
                        jax.tree_util.tree_leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_1f1b_trains(self):
        import jax
        from paddle_tpu.distributed.fleet.pp_compiled import Compiled1F1B
        pipe, stage_fn, mesh, params, x, y_tgt, S = self._setup()

        def loss_fn(y, label):
            return jnp.mean((y - label) ** 2)

        fifo = Compiled1F1B(stage_fn, loss_fn, mesh, num_microbatches=8)

        @jax.jit
        def step(params, x, y):
            l, g = fifo.loss_and_grads(params, x, y)
            return l, jax.tree_util.tree_map(
                lambda p, gg: p - 0.5 * gg, params, g)

        with mesh:
            losses = []
            for _ in range(5):
                l, params = step(params, x, y_tgt)
                losses.append(float(l))
        assert losses[-1] < losses[0]

    @pytest.mark.slow
    def test_1f1b_activation_memory_below_gpipe(self):
        """VERDICT round-2 #5 'done' criterion: at M=8 the 1F1B program's
        peak live activation state must be measurably below compiled
        GPipe's. Compare XLA's own accounting (temp buffer bytes) of the
        two compiled loss+grad programs; skip if this backend's
        memory_analysis is unavailable."""
        import jax
        from paddle_tpu.distributed.fleet.pp_compiled import Compiled1F1B
        M = 8
        pipe, stage_fn, mesh, params, x, y_tgt, S = self._setup(
            S=4, M=M, D=64, mb=16)

        def loss_fn(y, label):
            return jnp.mean((y - label) ** 2)

        fifo = Compiled1F1B(stage_fn, loss_fn, mesh, num_microbatches=M)

        def gpipe_loss(params, x, y):
            return jnp.mean(jax.vmap(loss_fn)(pipe(params, x), y))

        with mesh:
            c_1f1b = jax.jit(fifo.loss_and_grads).lower(
                params, x, y_tgt).compile()
            c_gpipe = jax.jit(jax.value_and_grad(gpipe_loss)).lower(
                params, x, y_tgt).compile()
        try:
            m1 = c_1f1b.memory_analysis()
            m2 = c_gpipe.memory_analysis()
            t1, t2 = m1.temp_size_in_bytes, m2.temp_size_in_bytes
        except Exception:
            pytest.skip("memory_analysis unavailable on this backend")
        if not t1 or not t2:
            pytest.skip("backend reports zero temp sizes")
        assert t1 < t2, f"1f1b temp {t1} not below gpipe temp {t2}"

    @pytest.mark.parametrize("S,V", [(2, 2), (4, 2), (2, 3)])
    def test_interleaved_matches_sequential(self, S, V):
        """Compiled interleaved VPP (V chunks/stage, ring ppermute with
        chunk-boundary wraparound) must match the sequential model."""
        import jax
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.pp_compiled import (
            CompiledInterleaved)
        L, M, D, mb = V * S, 8, 12, 4
        mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
        rng = np.random.RandomState(S * 10 + V)
        W = jnp.asarray(rng.randn(S, V, D, D) * 0.1, jnp.float32)
        B = jnp.asarray(rng.randn(S, V, D) * 0.1, jnp.float32)

        def chunk_fn(p, x):
            w, b = p
            return jnp.tanh(x @ w + b)

        def loss_fn(y, label):
            return jnp.mean((y - label) ** 2)

        x = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        y = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        vpp = CompiledInterleaved(chunk_fn, loss_fn, mesh,
                                  num_microbatches=M, num_chunks=V)
        with mesh:
            lp, gp = jax.jit(vpp.loss_and_grads)((W, B), x, y)

        def loss_seq(params, x, y):
            Wp, Bp = params

            def fwd(v):
                for c in range(L):   # chunk c on stage c%S, slot c//S
                    v = chunk_fn((Wp[c % S, c // S],
                                  Bp[c % S, c // S]), v)
                return v
            return jnp.mean(jax.vmap(
                lambda a, b: loss_fn(fwd(a), b))(x, y))

        ls, gs = jax.jit(jax.value_and_grad(loss_seq))((W, B), x, y)
        assert abs(float(lp) - float(ls)) < 1e-6
        for a, b in zip(jax.tree_util.tree_leaves(gp),
                        jax.tree_util.tree_leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_interleaved_split_dw_matches(self):
        """ZB dW/dX split on the VPP schedule: identical grads."""
        import jax
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.pp_compiled import (
            CompiledInterleaved)
        S, V, M, D, mb = 2, 2, 6, 12, 4
        mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
        rng = np.random.RandomState(3)
        params = (jnp.asarray(rng.randn(S, V, D, D) * 0.1, jnp.float32),
                  jnp.asarray(rng.randn(S, V, D) * 0.1, jnp.float32))

        def chunk_fn(p, x):
            w, b = p
            return jnp.tanh(x @ w + b)

        def loss_fn(y, label):
            return jnp.mean((y - label) ** 2)

        x = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        y = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        plain = CompiledInterleaved(chunk_fn, loss_fn, mesh, M, V)
        zb = CompiledInterleaved(chunk_fn, loss_fn, mesh, M, V,
                                 split_dw=True)
        with mesh:
            l0, g0 = jax.jit(plain.loss_and_grads)(params, x, y)
            l1, g1 = jax.jit(zb.loss_and_grads)(params, x, y)
        assert abs(float(l0) - float(l1)) < 1e-7
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-7)

    @pytest.mark.slow
    def test_interleaved_trains(self):
        import jax
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.pp_compiled import (
            CompiledInterleaved)
        S, V, M, D, mb = 2, 2, 4, 8, 4
        mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
        rng = np.random.RandomState(9)
        params = (jnp.asarray(rng.randn(S, V, D, D) * 0.1, jnp.float32),
                  jnp.asarray(rng.randn(S, V, D) * 0.1, jnp.float32))

        def chunk_fn(p, x):
            w, b = p
            return jnp.tanh(x @ w + b)

        def loss_fn(y, label):
            return jnp.mean((y - label) ** 2)

        vpp = CompiledInterleaved(chunk_fn, loss_fn, mesh,
                                  num_microbatches=M, num_chunks=V)
        x = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        y = jnp.asarray(rng.randn(M, mb, D), jnp.float32)

        @jax.jit
        def step(params, x, y):
            l, g = vpp.loss_and_grads(params, x, y)
            return l, jax.tree_util.tree_map(
                lambda p, gg: p - 0.5 * gg, params, g)

        with mesh:
            losses = []
            for _ in range(5):
                l, params = step(params, x, y)
                losses.append(float(l))
        assert losses[-1] < losses[0]

    def test_pp_with_dp_axis(self):
        """pp pipeline composed with a dp axis on a 2x4 mesh."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from paddle_tpu.distributed.fleet.pp_compiled import CompiledPipeline
        S, M, D, mb = 4, 4, 8, 8
        devs = np.array(jax.devices()[:8]).reshape(2, S)
        mesh = Mesh(devs, ("dp", "pp"))
        rng = np.random.RandomState(1)
        W = jnp.asarray(rng.randn(S, 1, D, D) * 0.1, jnp.float32)
        B = jnp.asarray(rng.randn(S, 1, D) * 0.1, jnp.float32)

        def stage_fn(p, x):
            w, b = p
            return jnp.tanh(x @ w[0] + b[0])

        pipe = CompiledPipeline(stage_fn, mesh, num_microbatches=M)
        x = jnp.asarray(rng.randn(M, mb, D), jnp.float32)

        def fwd_seq(v):
            for s in range(S):
                v = stage_fn((W[s], B[s]), v)
            return v

        with mesh:
            y = jax.jit(lambda p, x: pipe(p, x))((W, B), x)
        ref = jax.vmap(fwd_seq)(x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   atol=1e-5)
