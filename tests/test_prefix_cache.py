"""Radix prefix cache (inference/prefix_cache.py): refcount/eviction/
COW invariants over the BlockManager, suffix-only prefill on a warm
cache, exact greedy parity with the cold path, and the persistent
``generate_paged(prefix_cache=...)`` store."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.models import llama
from paddle_tpu.inference import (GenerationConfig, PagedKVCacheStore,
                                  ServingEngine, generate)
from paddle_tpu.inference.generation import generate_paged
from paddle_tpu.inference.prefix_cache import PrefixCache
from paddle_tpu.ops.paged_attention import BlockManager

CFG = llama.LlamaConfig(vocab_size=97, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        max_position_embeddings=128, dtype=jnp.float32,
                        remat=False)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _engine(params, **kw):
    kw.setdefault("capacity", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("prefix_cache", True)
    return ServingEngine(params, CFG, **kw)


def _want(params, p, g):
    return np.asarray(generate(params, jnp.asarray(p)[None], CFG,
                               g))[0, len(p):].tolist()


# -- BlockManager refcount invariants ---------------------------------

class TestRefcounts:
    def test_refcount_never_negative(self):
        mgr = BlockManager(4, 4, 4)
        p = mgr.alloc_page()
        assert mgr.refcount[p] == 1
        assert mgr.decref(p) is True          # 1 -> 0: freed
        with pytest.raises(RuntimeError, match="negative"):
            mgr.decref(p)

    def test_incref_on_free_page_rejected(self):
        mgr = BlockManager(4, 4, 4)
        p = mgr.alloc_page()
        mgr.decref(p)
        with pytest.raises(RuntimeError, match="unowned"):
            mgr.incref(p)

    def test_shared_page_survives_one_release(self):
        mgr = BlockManager(8, 4, 8)
        t1 = mgr.allocate(1, 8)               # two pages, rc 1 each
        mgr.attach(2, t1)                     # seq 2 shares both (rc 2)
        mgr.allocate(2, 8)
        free_before = len(mgr.free)
        mgr.release(1)
        assert all(mgr.refcount[p] == 1 for p in mgr.tables[2])
        assert len(mgr.free) == free_before   # shared pages survived
        mgr.release(2)
        assert len(mgr.free) == 8
        assert (mgr.refcount == 0).all()

    def test_fork_allocates_fresh_page(self):
        mgr = BlockManager(4, 4, 4)
        src = mgr.alloc_page()
        dst = mgr.fork(src)
        assert dst != src
        assert mgr.refcount[src] == 1         # pin dropped after fork
        assert mgr.refcount[dst] == 1


# -- tree-level invariants (no model needed) --------------------------

def _tree(num_blocks=32, bs=4):
    mgr = BlockManager(num_blocks, bs, num_blocks)
    copies = []
    cache = PrefixCache(mgr, bs, copy_page=lambda s, d:
                        copies.append((s, d)))
    return mgr, cache, copies


def _insert_released(mgr, cache, toks, pages):
    """Insert as a finished request would: the tree adopts the pages,
    then the request's own references are dropped — cached pages end at
    refcount 1 (tree-only)."""
    cache.insert(toks, pages)
    for p in pages:
        mgr.decref(p)


class TestRadixTree:
    def test_insert_match_full_and_tail(self):
        mgr, cache, copies = _tree()
        toks = list(range(10))                 # 2 full pages + 2-tail
        pages = [mgr.alloc_page() for _ in range(3)]
        _insert_released(mgr, cache, toks, pages)
        assert cache.cached_pages == 3
        full, tail, c = cache.match(toks)
        assert [n.page for n in full] == pages[:2]
        assert tail is not None and tail.page == pages[2] and c == 2

    def test_acquire_cow_forks_tail_before_any_write(self):
        mgr, cache, copies = _tree()
        toks = list(range(10))
        pages = [mgr.alloc_page() for _ in range(3)]
        _insert_released(mgr, cache, toks, pages)
        got = cache.acquire(toks + [50, 51], limit=11, total_pages=4)
        acq_pages, matched, shared = got
        assert matched == 10 and shared == 2
        # the tail page was forked: the request got a COPY, and the
        # device copy ran BEFORE the page was handed out
        assert acq_pages[:2] == pages[:2]
        assert acq_pages[2] != pages[2]
        assert copies == [(pages[2], acq_pages[2])]
        assert mgr.refcount[pages[2]] == 1     # original still tree-only

    def test_match_capped_at_limit(self):
        mgr, cache, _ = _tree()
        toks = list(range(8))                  # exactly 2 full pages
        pages = [mgr.alloc_page() for _ in range(2)]
        _insert_released(mgr, cache, toks, pages)
        # limit 7 (= S-1 for an 8-token prompt): the second page cannot
        # be shared whole — it must come back as a 3-token COW fork
        acq_pages, matched, shared = cache.acquire(toks, limit=7,
                                                   total_pages=2)
        assert shared == 1 and matched == 7
        assert acq_pages[0] == pages[0] and acq_pages[1] != pages[1]

    def test_acquire_waits_when_only_fork_source_is_evictable(self):
        """Backpressure must account for the fork pinning its source:
        with an empty free list and the would-be-forked tail the only
        evictable page, acquire must WAIT (None, nothing leaked) — not
        crash allocation mid-fork."""
        mgr, cache, copies = _tree(num_blocks=2)
        pages = [mgr.alloc_page(), mgr.alloc_page()]
        _insert_released(mgr, cache, list(range(6)), pages)
        assert not mgr.free
        got = cache.acquire(list(range(8)), limit=7, total_pages=2)
        assert got is None
        assert mgr.refcount[pages[0]] == 1      # pins rolled back
        assert mgr.refcount[pages[1]] == 1
        assert not copies                        # no half-done fork

    def test_eviction_only_frees_refcount_zero(self):
        mgr, cache, _ = _tree(num_blocks=8)
        toks = list(range(16))                 # 4 full pages
        pages = [mgr.alloc_page() for _ in range(4)]
        _insert_released(mgr, cache, toks, pages)
        # share the first two pages with a live "request"
        acq_pages, matched, shared = cache.acquire(
            toks[:9], limit=8, total_pages=3)
        assert shared == 2
        freed = cache.evict(100)               # ask for everything
        # only the two unpinned tree pages could go; pinned ones stayed
        assert freed == 2
        assert mgr.refcount[pages[2]] == 0 and mgr.refcount[pages[3]] == 0
        assert mgr.refcount[pages[0]] == 2 and mgr.refcount[pages[1]] == 2
        assert (mgr.refcount >= 0).all()

    def test_lru_evicts_oldest_first(self):
        mgr, cache, _ = _tree(num_blocks=16)
        a = [mgr.alloc_page() for _ in range(2)]
        b = [mgr.alloc_page() for _ in range(2)]
        _insert_released(mgr, cache, [1, 2, 3, 4, 5, 6, 7, 8], a)
        _insert_released(mgr, cache, [9, 10, 11, 12, 13, 14, 15, 16], b)
        cache.acquire([9, 10, 11, 12, 13], limit=5, total_pages=2)
        # branch b was touched more recently; evicting 2 takes branch a
        assert cache.evict(2) == 2
        assert mgr.refcount[a[0]] == 0 and mgr.refcount[a[1]] == 0

    def test_partial_tail_upgrade_rekeys_parent_for_eviction(self):
        """REVIEW regression: the upgrade-in-place path replaced a
        partial tail's tokens without rekeying its parent's children
        dict, so a later eviction's keyed delete raised KeyError.
        Repro: insert a short sequence, extend it via a second
        insert, then evict everything."""
        mgr, cache, _ = _tree()
        p1 = [mgr.alloc_page() for _ in range(2)]
        _insert_released(mgr, cache, [1, 2, 3, 4, 5, 6], p1)
        got = cache.acquire([1, 2, 3, 4, 5, 6, 7, 8], limit=7,
                            total_pages=2)
        assert got is not None
        acq_pages, matched, shared = got
        assert matched == 6 and shared == 1    # full page + 2-token fork
        _insert_released(mgr, cache, [1, 2, 3, 4, 5, 6, 7, 8], acq_pages)
        # the tail node was upgraded in place to a full page; it must be
        # findable both by match() and by its parent's dict key
        full, tail, c = cache.match([1, 2, 3, 4, 5, 6, 7, 8])
        assert [n.tokens for n in full] == [(1, 2, 3, 4), (5, 6, 7, 8)]
        assert (5, 6, 7, 8) in full[0].children
        assert cache.evict(100) == 2           # keyed delete must not raise
        assert (mgr.refcount == 0).all()
        assert len(mgr.free) == mgr.num_blocks

    def test_divergent_insert_keeps_both_branches(self):
        mgr, cache, _ = _tree()
        p1 = [mgr.alloc_page() for _ in range(2)]
        p2 = [mgr.alloc_page() for _ in range(2)]
        _insert_released(mgr, cache, [1, 2, 3, 4, 5, 6, 7, 8], p1)
        _insert_released(mgr, cache, [1, 2, 3, 9, 5, 6, 7, 8], p2)
        assert cache.cached_pages == 4
        full, tail, c = cache.match([1, 2, 3, 9, 5])
        assert [n.page for n in full] == [p2[0]]
        full, tail, c = cache.match([1, 2, 3, 4, 5])
        assert [n.page for n in full] == [p1[0]]


# -- engine-level behavior --------------------------------------------

def test_warm_cache_exact_parity_and_suffix_only_prefill(params):
    """A second request sharing the prompt prefills ONLY its suffix
    (one 1-token chunk instead of two bucket chunks) and its greedy
    output is bit-identical to the cold path and to generate()."""
    rng = np.random.RandomState(0)
    eng = _engine(params)
    p = rng.randint(0, 97, (20,)).astype(np.int32)
    g = GenerationConfig(max_new_tokens=5, greedy=True)
    r1 = eng.submit(p, g)
    eng.drain()
    cold_chunks = eng.counters["prefill_chunks"]
    assert cold_chunks == 2                     # 16-bucket + 4 tokens
    r2 = eng.submit(p, g)
    eng.drain()
    assert eng.counters["prefill_chunks"] - cold_chunks == 1
    want = _want(params, p, g)
    assert r1.tokens == want
    assert r2.tokens == want
    m = eng.metrics()["prefix_cache"]
    assert m["hits"] == 1 and m["misses"] == 1
    assert m["tokens_skipped"] == 19            # capped at S-1
    assert m["cow_forks"] == 1                  # 3-token tail fork
    assert m["shared_pages"] == 4


def test_three_request_shared_prefix_stream_parity(params):
    """3 requests sharing a 12-token system prefix with distinct
    continuations, interleaved through 2 slots: every output must equal
    cold-cache generate() exactly, and later requests must skip the
    shared pages."""
    rng = np.random.RandomState(1)
    eng = _engine(params, capacity=2)
    sys_prefix = rng.randint(0, 97, (12,)).astype(np.int32)
    g = GenerationConfig(max_new_tokens=5, greedy=True)
    reqs = []
    for i in range(3):
        tail = rng.randint(0, 97, (5 + i,)).astype(np.int32)
        p = np.concatenate([sys_prefix, tail])
        reqs.append((p, eng.submit(p, g)))
    eng.drain()
    for p, r in reqs:
        assert r.tokens == _want(params, p, g), "divergent continuation"
    m = eng.metrics()["prefix_cache"]
    assert m["hits"] >= 1
    assert m["tokens_skipped"] >= 12            # the shared system pages
    c = eng.counters
    assert c["decode_traces"] == 1              # no retrace from hits
    assert all(n <= 1 for n in c["prefill_traces"].values()), c


def test_cow_protects_shared_page_from_divergent_writer(params):
    """A request that shares a prefix then diverges writes into its COW
    fork; re-running the ORIGINAL prompt afterwards must still match
    cold-cache generate() exactly (the cached page was not corrupted)."""
    rng = np.random.RandomState(2)
    eng = _engine(params)
    g = GenerationConfig(max_new_tokens=4, greedy=True)
    base = rng.randint(0, 97, (10,)).astype(np.int32)
    eng.submit(base, g)
    eng.drain()
    # diverges at position 9 — inside the cached partial tail page
    div = base.copy()
    div[9] = (div[9] + 1) % 97
    div = np.concatenate([div, rng.randint(0, 97, (6,)).astype(np.int32)])
    eng.submit(div, g)
    eng.drain()
    assert eng.metrics()["prefix_cache"]["cow_forks"] >= 1
    r = eng.submit(base, g)
    eng.drain()
    assert r.tokens == _want(params, base, g)


def test_in_flight_prefix_sharing(params):
    """The prompt is indexed when its PREFILL completes, not at finish:
    a second request arriving while the first still decodes must hit
    the cache and share live (refcount >= 2) pages — and both outputs
    stay exact."""
    rng = np.random.RandomState(8)
    eng = _engine(params)
    g = GenerationConfig(max_new_tokens=8, greedy=True)
    p = rng.randint(0, 97, (12,)).astype(np.int32)
    r1 = eng.submit(p, g)
    eng.step()                  # admits + completes r1's prefill
    assert not r1.done
    tail = rng.randint(0, 97, (4,)).astype(np.int32)
    p2 = np.concatenate([p, tail])
    r2 = eng.submit(p2, g)      # r1 still decoding
    eng.drain()
    m = eng.metrics()["prefix_cache"]
    assert m["hits"] == 1 and m["tokens_skipped"] >= 12
    assert r1.tokens == _want(params, p, g)
    assert r2.tokens == _want(params, p2, g)


def test_eviction_under_undersized_pool(params):
    """Distinct prompts through a pool that cannot hold the tree force
    LRU eviction; outputs stay exact, pages are conserved, and no page
    with refcount > 0 is ever freed (free-list pages all have rc 0)."""
    rng = np.random.RandomState(3)
    eng = _engine(params, capacity=2, num_blocks=14, max_seq_len=32)
    g = GenerationConfig(max_new_tokens=4, greedy=True)
    reqs = [(p := rng.randint(0, 97, (16,)).astype(np.int32),
             eng.submit(p, g)) for _ in range(6)]
    eng.drain()
    for p, r in reqs:
        assert r.tokens == _want(params, p, g)
    m = eng.metrics()["prefix_cache"]
    assert m["evicted_pages"] > 0
    rc = eng.mgr.refcount
    assert (rc >= 0).all()
    assert all(rc[p] == 0 for p in eng.mgr.free)
    # conservation: free + cached(tree) + scratch == pool
    assert len(eng.mgr.free) + m["cached_pages"] + 1 == eng.num_blocks


def test_eviction_after_tail_upgrade(params):
    """REVIEW regression, engine path: a finished request's insert
    upgrades the partial tail node its own prefill-time insert created
    (prompt length not page-aligned); eviction of that upgraded node
    must find it under its parent's rekeyed dict entry instead of
    KeyError-ing mid-admission."""
    rng = np.random.RandomState(7)
    eng = _engine(params, capacity=2, num_blocks=14, max_seq_len=32)
    g = GenerationConfig(max_new_tokens=3, greedy=True)
    reqs = [(p := rng.randint(0, 97, (10,)).astype(np.int32),
             eng.submit(p, g)) for _ in range(6)]
    eng.drain()
    for p, r in reqs:
        assert r.tokens == _want(params, p, g)
    m = eng.metrics()["prefix_cache"]
    assert m["evicted_pages"] > 0
    rc = eng.mgr.refcount
    assert (rc >= 0).all()
    assert all(rc[p] == 0 for p in eng.mgr.free)
    assert len(eng.mgr.free) + m["cached_pages"] + 1 == eng.num_blocks


def test_int8_engine_participates(params):
    """Engine-global static scales make int8 pages shareable: a warm
    repeat of the same prompt hits the cache and reproduces the cold
    int8 tokens exactly."""
    rng = np.random.RandomState(4)
    eng = _engine(params, cache_dtype="int8")
    g = GenerationConfig(max_new_tokens=5, greedy=True)
    p = rng.randint(0, 97, (12,)).astype(np.int32)
    r1 = eng.submit(p, g)
    eng.drain()
    r2 = eng.submit(p, g)
    eng.drain()
    assert r1.tokens == r2.tokens
    assert eng._k_pools.dtype == jnp.int8
    assert eng.metrics()["prefix_cache"]["hits"] == 1


def test_mixed_stream_with_cache_stays_zero_retrace(params):
    """A 12-request mixed stream (some shared prefixes, some cold, some
    sampled) through the cached engine keeps the PR-1 trace bar: one
    decode program, <=1 trace per prefill bucket."""
    rng = np.random.RandomState(5)
    eng = _engine(params, capacity=3)
    sysp = rng.randint(0, 97, (8,)).astype(np.int32)
    subs = []
    for i in range(12):
        S = int(rng.randint(3, 15))
        p = rng.randint(0, 97, (S,)).astype(np.int32)
        if i % 2:
            p = np.concatenate([sysp, p[:6]])
        g = GenerationConfig(max_new_tokens=int(rng.randint(2, 6)),
                             greedy=bool(i % 3), temperature=0.7)
        subs.append(eng.submit(p, g))
        eng.step()
    eng.drain()
    assert all(r.done for r in subs)
    c = eng.counters
    assert c["decode_traces"] == 1, c
    assert all(n <= 1 for n in c["prefill_traces"].values()), c


# -- generate_paged store ---------------------------------------------

def test_generate_paged_prefix_store_parity(params):
    """Warm-store greedy output is bit-identical to the cold call and
    to generate(); the warm call skips the cached prefix pages."""
    store = PagedKVCacheStore(CFG, block_size=4, num_blocks=64)
    rng = np.random.RandomState(6)
    p = jnp.asarray(rng.randint(0, 97, (2, 13)), jnp.int32)
    g = GenerationConfig(max_new_tokens=6, greedy=True)
    cold = np.asarray(generate_paged(params, p, CFG, g, block_size=4,
                                     prefix_cache=store))
    skipped0 = store.cache.stats["tokens_skipped"]
    warm = np.asarray(generate_paged(params, p, CFG, g, block_size=4,
                                     prefix_cache=store))
    ref = np.asarray(generate(params, p, CFG, g))
    np.testing.assert_array_equal(cold, warm)
    np.testing.assert_array_equal(cold, ref)
    assert store.cache.stats["tokens_skipped"] > skipped0
    # all request pages returned: free + tree + scratch == pool
    assert (len(store.mgr.free) + store.cache.cached_pages + 1
            == store.num_blocks)


def test_generate_paged_prefix_store_rejects_int8(params):
    store = PagedKVCacheStore(CFG, block_size=4, num_blocks=32)
    p = jnp.zeros((1, 6), jnp.int32)
    with pytest.raises(ValueError, match="int8"):
        generate_paged(params, p, CFG,
                       GenerationConfig(max_new_tokens=2, greedy=True),
                       block_size=4, cache_dtype="int8",
                       prefix_cache=store)
