"""Profiler subsystem tests (reference test model: test/legacy_test/
test_profiler.py, test_newprofiler.py)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.profiler import (Profiler, ProfilerState, ProfilerTarget,
                                 RecordEvent, make_scheduler,
                                 export_chrome_tracing, benchmark)
from paddle_tpu.profiler.record_event import get_host_tracer
from paddle_tpu.profiler.statistics import aggregate, build_summary


def test_make_scheduler_cycle():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=2,
                           skip_first=1)
    states = [sched(i) for i in range(10)]
    assert states[0] == ProfilerState.CLOSED          # skip_first
    assert states[1] == ProfilerState.CLOSED
    assert states[2] == ProfilerState.READY
    assert states[3] == ProfilerState.RECORD
    assert states[4] == ProfilerState.RECORD_AND_RETURN
    assert states[5] == ProfilerState.CLOSED          # cycle 2
    assert states[8] == ProfilerState.RECORD_AND_RETURN
    assert states[9] == ProfilerState.CLOSED          # repeat exhausted


def test_record_event_and_op_tracing(tmp_path):
    traces = []
    done = export_chrome_tracing(str(tmp_path))
    with Profiler(targets=[ProfilerTarget.CPU], on_trace_ready=done) as p:
        with RecordEvent("user_scope"):
            x = paddle.to_tensor(np.ones((4, 4), np.float32))
            y = x @ x + x
        p.step(num_samples=4)
        x2 = paddle.to_tensor(np.ones((2, 2), np.float32))
        _ = x2 * 2
    events = [e.name for e in get_host_tracer().events()]
    # stop() exports; host tracer should have seen user scope + ops
    files = list(tmp_path.iterdir())
    assert files, "chrome trace file written"
    data = json.load(open(files[0]))
    names = [e.get("name") for e in data["traceEvents"]]
    assert "user_scope" in names
    assert "matmul" in names or "multiply" in names
    get_host_tracer().clear()


def test_summary_and_aggregate():
    tracer = get_host_tracer()
    tracer.clear()
    tracer.start()
    with RecordEvent("alpha"):
        pass
    with RecordEvent("alpha"):
        pass
    with RecordEvent("beta"):
        pass
    tracer.stop()
    stats = aggregate(tracer.events())
    assert stats["alpha"]["calls"] == 2
    assert stats["beta"]["calls"] == 1
    text = build_summary(tracer.events())
    assert "alpha" in text and "Ratio" in text
    tracer.clear()


def test_benchmark_timer_ips():
    bm = benchmark()
    bm.reset()
    bm.begin()
    for _ in range(3):
        bm.step(num_samples=32)
    bm.end()
    info = bm.step_info()
    assert "ips" in info
    assert bm.batch_cost.get_ips_average() > 0


def test_profiler_scheduler_driven_steps(tmp_path):
    exported = []

    def on_ready(prof):
        prof._export_chrome(str(tmp_path / f"t{len(exported)}.json"))
        exported.append(1)

    sched = make_scheduler(closed=0, ready=1, record=1, repeat=2)
    p = Profiler(targets=[ProfilerTarget.CPU], scheduler=sched,
                 on_trace_ready=on_ready)
    p.start()
    for _ in range(6):
        _ = paddle.to_tensor([1.0]) + 1.0
        p.step()
    p.stop()
    assert len(exported) >= 2
    get_host_tracer().clear()
