"""tools/program_audit.py — the CI audit gate. Canned-program CLI
contract (findings JSON schema, baseline diff semantics, exit codes)
plus the tier-1 gate itself: every catalog program audited against the
committed AUDIT_BASELINE.json with zero new findings."""
import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.audit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "program_audit.py")
COMMITTED_BASELINE = os.path.join(REPO, "AUDIT_BASELINE.json")


def _run(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    return subprocess.run([sys.executable, CLI, *args],
                          capture_output=True, text=True, env=env,
                          timeout=600)


# -- the tier-1 gate (in-process: one build+audit of the full catalog) --

def test_audit_gate_catalog_clean_vs_committed_baseline():
    """THE gate: all registered bench programs (trainer step, fused
    optimizer, serving decode + prefill buckets, page copier,
    collectives) audited against the committed baseline — no new
    findings. A regression here means a rule pass caught something the
    baseline does not accept: fix the program, or consciously accept
    the finding with --write-baseline."""
    from paddle_tpu.analysis import (audit_spec, diff_findings,
                                     load_baseline)
    from paddle_tpu.analysis.catalog import (CATALOG_PROGRAMS,
                                             build_catalog)
    specs = build_catalog()
    assert sorted(s.name for s in specs) == sorted(CATALOG_PROGRAMS)
    reports = [audit_spec(s) for s in specs]
    baseline = load_baseline(COMMITTED_BASELINE)
    new, _fixed = diff_findings(reports, baseline)
    assert new == [], "\n".join(
        f"{f.fingerprint}: {f.message}" for f in new)


def test_demo_regression_fails_the_gate_in_process():
    """The injected regression (pre-fix AdamW) must produce NEW
    findings vs the committed baseline — the gate can actually fail."""
    from paddle_tpu.analysis import (audit_spec, diff_findings,
                                     load_baseline)
    from paddle_tpu.analysis.catalog import build_demo_regression
    rep = audit_spec(build_demo_regression())
    new, _ = diff_findings([rep], load_baseline(COMMITTED_BASELINE))
    codes = {f.code for f in new}
    assert "F64_PROMOTION" in codes and "CARRY_DTYPE_DRIFT" in codes


def test_demo_tp_regression_fails_the_gate_in_process():
    """The second injected regression (mismatched-mesh-axis sharded
    decode body) must produce a NEW UNKNOWN_COLLECTIVE_AXIS finding vs
    the committed baseline — the collective rule bites on a real
    tensor-parallel serving program."""
    from paddle_tpu.analysis import (audit_spec, diff_findings,
                                     load_baseline)
    from paddle_tpu.analysis.catalog import build_demo_tp_regression
    rep = audit_spec(build_demo_tp_regression())
    new, _ = diff_findings([rep], load_baseline(COMMITTED_BASELINE))
    assert "UNKNOWN_COLLECTIVE_AXIS" in {f.code for f in new}


# -- CLI contract (subprocess: canned single-program runs) --------------

def test_cli_json_schema_and_baseline_diff(tmp_path):
    out_json = str(tmp_path / "findings.json")
    base = str(tmp_path / "baseline.json")
    # write a baseline for ONE canned program (page copier: cheapest)
    r = _run("--program", "serving_page_copy", "--baseline", base,
             "--write-baseline", "--json", out_json, "--quiet")
    assert r.returncode == 0, r.stderr
    with open(out_json) as fh:
        doc = json.load(fh)
    assert set(doc.keys()) == {"version", "programs", "summary"}
    assert list(doc["programs"]) == ["serving_page_copy"]
    prog = doc["programs"]["serving_page_copy"]
    assert set(prog.keys()) == {"program", "findings", "rules_run",
                                "meta"}
    assert set(prog["rules_run"]) == {
        "dtype_promotion_rule", "donation_rule", "retrace_hazard_rule",
        "collective_consistency_rule", "constant_bloat_rule"}
    for f in prog["findings"]:
        assert set(f.keys()) == {"rule", "code", "severity", "program",
                                 "site", "message", "detail",
                                 "fingerprint"}
    with open(base) as fh:
        bdoc = json.load(fh)
    assert set(bdoc.keys()) == {"version", "findings"}
    # gate against the fresh baseline: clean, exit 0
    r2 = _run("--program", "serving_page_copy", "--baseline", base)
    assert r2.returncode == 0, r2.stderr


def test_cli_nonzero_exit_on_injected_regression(tmp_path):
    """--demo-regression injects the pre-fix AdamW program: the gate
    must fail (exit 2) and name the finding on stderr."""
    base = str(tmp_path / "baseline.json")
    r = _run("--program", "serving_page_copy", "--baseline", base,
             "--write-baseline", "--quiet")
    assert r.returncode == 0, r.stderr
    r2 = _run("--program", "serving_page_copy", "--baseline", base,
              "--demo-regression", "--quiet")
    assert r2.returncode == 2
    assert "GATE FAILED" in r2.stderr
    assert "F64_PROMOTION" in r2.stderr
    # the second specimen: mismatched mesh axis on the real sharded
    # serving decode body
    assert "UNKNOWN_COLLECTIVE_AXIS" in r2.stderr
