"""Quantized serving (r18): int8/int4 weights on the decode + prefill
hot paths — the PTQ harness (quantization/ptq.py), the quantized-weight
megakernel variants (fused_decode_block / fused_prefill_block), and the
engine/generate routing behind ``weight_quant=``.

Parity contract: wherever dispatch selects the ``unfused`` composition
(always on CPU/interpret), the quantized route is BIT-identical to
dequantize-then-matmul by construction (every unfused matmul site goes
through the ONE ``maybe_dequantize`` helper). The Pallas megakernels
themselves (forced, interpret mode) dequantize in-register in the
matmul epilogue and match the composition to fp32 roundoff. int8
weights hold greedy output within a small documented flip budget vs fp
on the engine stream; int4 is a bandwidth/accuracy trade the bench
quantifies (random un-finetuned test weights flip far more than real
checkpoints — only int8 carries an engine-level budget here).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.models import llama
from paddle_tpu.inference import GenerationConfig, ServingEngine
from paddle_tpu.inference.generation import (_fused_decode_step,
                                             _paged_decode_step,
                                             generate_paged)
from paddle_tpu.ops.pallas import fused_decode_block as fdb
from paddle_tpu.ops.pallas import fused_prefill_block as fpb
from paddle_tpu.quantization import ptq, quanters

pytestmark = pytest.mark.quant

CFG = llama.LlamaConfig(vocab_size=97, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        max_position_embeddings=128, dtype=jnp.float32,
                        remat=False)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _engine(params, **kw):
    kw.setdefault("capacity", 3)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("max_seq_len", 64)
    return ServingEngine(params, CFG, **kw)


# ---------------------------------------------------------------------------
# quanters: pack/unpack round trip + the fixed scale contract
# ---------------------------------------------------------------------------
def test_int4_pack_unpack_byte_roundtrip():
    rng = np.random.RandomState(0)
    q = rng.randint(-7, 8, (12, 10)).astype(np.int8)
    for axis in (0, 1):
        p = quanters.pack_int4(q, axis=axis)
        assert p.dtype == np.int8
        assert p.shape[axis] == q.shape[axis] // 2
        u = np.asarray(quanters.unpack_int4(p, axis=axis))
        np.testing.assert_array_equal(u, q)
    # packing an ODD axis is a structural error, not silent truncation
    with pytest.raises(ValueError, match="odd"):
        quanters.pack_int4(q[:11], axis=0)


def test_quantize_scale_contract_flat_f32_symmetric():
    """The kernel contract the satellite fixed: per-OUTPUT-channel FLAT
    f32 scales (no keepdims) and a symmetric integer range."""
    rng = np.random.RandomState(1)
    w = rng.randn(16, 6).astype(np.float32)
    q8, s8 = quanters.quantize_to_int8(w, axis=-1)
    assert s8.shape == (6,) and s8.dtype == np.float32
    assert q8.min() >= -127 and q8.max() <= 127
    q4, s4 = quanters.quantize_to_int4(w, axis=-1)
    assert s4.shape == (6,) and q4.min() >= -7 and q4.max() <= 7
    # dequant error bounded by half a step per channel
    assert np.all(np.abs(q8 * s8[None] - w) <= s8[None] / 2 + 1e-7)
    # int8_matmul consumes the flat scales directly
    x = rng.randn(8, 16).astype(np.float32)
    xs = np.abs(x).max() / 127.0
    xq = np.clip(np.round(x / xs), -127, 127).astype(np.int8)
    out = np.asarray(quanters.int8_matmul(jnp.asarray(xq),
                                          jnp.asarray(q8), xs, s8))
    assert out.shape == (8, 6)
    rel = np.abs(out - x @ w).max() / np.abs(x @ w).max()
    assert rel < 0.05


def test_dequantize_weight_infers_pack_axis():
    """down_proj packs its OUTPUT axis; everything else packs the
    contraction axis — dequantize_weight must reconstruct both from
    the byte-count/scale-length relation alone."""
    rng = np.random.RandomState(2)
    w = rng.randn(8, 6).astype(np.float32)
    for pack_axis in (0, 1):
        leaf = ptq.quantize_leaf(w, 4, pack_axis=pack_axis)
        deq = np.asarray(quanters.dequantize_weight(leaf))
        assert deq.shape == w.shape
        step = np.asarray(leaf["scale"])[None, :]
        assert np.all(np.abs(deq - w) <= step / 2 + 1e-7)


# ---------------------------------------------------------------------------
# PTQ harness
# ---------------------------------------------------------------------------
def test_ptq_tree_structure_and_mode_detection(params):
    assert ptq.weight_quant_mode(params) is None
    for bits, mode in ((8, "int8"), (4, "int4")):
        qp = ptq.quantize_weights(params, bits=bits)
        assert ptq.weight_quant_mode(qp) == mode
        layers = qp["layers"]
        for k, pack_axis in ptq.WQ_KEYS.items():
            leaf = layers[k]
            qkey = "qw8" if bits == 8 else "qw4"
            assert set(leaf) == {qkey, "scale"}
            orig = np.asarray(params["layers"][k]).shape
            got = tuple(leaf[qkey].shape)
            want = list(orig)
            if bits == 4:
                want[pack_axis] //= 2
            assert got == tuple(want), (k, got, want)
            # scales: per-layer, per-OUTPUT-channel (last axis), f32
            assert tuple(leaf["scale"].shape) == (orig[0], orig[-1])
            assert leaf["scale"].dtype == jnp.float32
        # norms / embedding / head stay fp
        assert layers["input_norm"].dtype == params["layers"][
            "input_norm"].dtype
        assert qp["embed_tokens"].dtype == params["embed_tokens"].dtype
    # double quantization is rejected, mismatched modes are rejected
    qp = ptq.quantize_weights(params, bits=8)
    with pytest.raises(ValueError, match="already"):
        ptq.quantize_weights(qp, bits=8)
    with pytest.raises(ValueError, match="int4"):
        ptq.ensure_quantized(qp, "int4")
    # ensure_quantized adopts a carried mode and validates a match
    same, mode = ptq.ensure_quantized(qp, None)
    assert same is qp and mode == "int8"
    same, mode = ptq.ensure_quantized(qp, "int8")
    assert same is qp and mode == "int8"


def test_ptq_scale_determinism(params):
    """One-shot PTQ is deterministic: two runs over the same fp tree
    produce byte-identical integer tiles and scales."""
    a = ptq.quantize_weights(params, bits=4)
    b = ptq.quantize_weights(params, bits=4)
    for k in ptq.WQ_KEYS:
        np.testing.assert_array_equal(np.asarray(a["layers"][k]["qw4"]),
                                      np.asarray(b["layers"][k]["qw4"]))
        np.testing.assert_array_equal(
            np.asarray(a["layers"][k]["scale"]),
            np.asarray(b["layers"][k]["scale"]))


def test_ptq_activation_aware_clip(params):
    """The first-prompt activation-aware path: absmax capture has the
    right shapes, the clip search never increases the activation-
    weighted error, and the result still serves."""
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, 97, (12,)).astype(np.int32)
    aa = ptq.activation_absmax(params, CFG, prompt)
    L, D, F = (CFG.num_hidden_layers, CFG.hidden_size,
               CFG.intermediate_size)
    E = CFG.num_attention_heads * CFG.head_dim
    assert aa["q_proj"].shape == (L, D)
    assert aa["o_proj"].shape == (L, E)
    assert aa["down_proj"].shape == (L, F)
    qp = ptq.quantize_weights(params, bits=4, act_absmax=aa)
    base = ptq.quantize_weights(params, bits=4)
    for k in ("q_proj", "down_proj"):
        a = np.asarray(aa[k], np.float64)[:, :, None] ** 2
        w = np.asarray(params["layers"][k], np.float64)
        err_aa = (((w - np.asarray(quanters.dequantize_weight(
            qp["layers"][k]), np.float64)) ** 2) * a).sum()
        err_pl = (((w - np.asarray(quanters.dequantize_weight(
            base["layers"][k]), np.float64)) ** 2) * a).sum()
        assert err_aa <= err_pl + 1e-12, k
    eng = _engine(qp)
    r = eng.submit(prompt, GenerationConfig(max_new_tokens=3,
                                            greedy=True))
    eng.drain()
    assert r.done and len(r.tokens) == 3


def test_weight_hbm_bytes_reduction(params):
    fp = ptq.weight_hbm_bytes(params)
    i8 = ptq.weight_hbm_bytes(ptq.quantize_weights(params, bits=8))
    i4 = ptq.weight_hbm_bytes(ptq.quantize_weights(params, bits=4))
    assert fp / i8 > 1.8          # fp32 test weights: ~4x - scales
    assert fp / i4 > 3.5


# ---------------------------------------------------------------------------
# kernel parity (forced Pallas, interpret) vs the dequant composition
# ---------------------------------------------------------------------------
def _attn_case(rng, B, D, KV, groups, hd, BS, MB, bits):
    H = KV * groups
    N = B * MB + 2
    dt = jnp.float32
    mk = lambda *s: jnp.asarray(rng.randn(*s) * 0.07, dt)  # noqa: E731
    x = mk(B, D)
    nw = jnp.asarray(rng.rand(D) + 0.5, dt)
    q = lambda w: ptq.quantize_leaf(w, bits)               # noqa: E731
    wq, wk, wv = q(mk(D, H * hd)), q(mk(D, KV * hd)), q(mk(D, KV * hd))
    wo = q(mk(H * hd, D))
    inv = 1.0 / (10000.0 ** (np.arange(0, hd, 2) / hd))
    t = np.arange(BS * MB)[:, None] * inv[None, :]
    sin = jnp.asarray(np.sin(t), jnp.float32)
    cos = jnp.asarray(np.cos(t), jnp.float32)
    bt = jnp.asarray(rng.permutation(N)[:B * MB].reshape(B, MB),
                     jnp.int32)
    lens = jnp.asarray([int(rng.randint(1, BS * MB)), 0][:B], jnp.int32)
    kp, vp = mk(N, BS, KV, hd), mk(N, BS, KV, hd)
    return (x, nw, wq, wk, wv, wo, sin, cos, kp, vp, bt, lens)


@pytest.mark.parametrize("bits", [8, 4], ids=["int8", "int4"])
def test_attn_kernel_parity_quantized_weights(bits):
    """Randomized ragged shapes: the quantized-weight megakernel
    (in-register dequant, epilogue scales) vs the dequantize-then-
    matmul composition — fp32 roundoff only, both sides reading the
    SAME quantized tree."""
    for seed in (0, 1):
        rng = np.random.RandomState(seed + bits)
        B = int(rng.randint(1, 3))
        KV = int(rng.choice([1, 2]))
        groups = int(rng.choice([1, 2]))
        hd = int(rng.choice([8, 16]))
        BS = int(rng.choice([4, 8]))
        MB = int(rng.randint(2, 5))
        D = int(rng.choice([32, 48, 64]))       # 48: D % 32 != 0 edge
        args = _attn_case(rng, B, D, KV, groups, hd, BS, MB, bits)
        xf, kf, vf = fdb.fused_attn_block_pallas(*args)
        xr, kr, vr = fdb.attn_block_ref(*args)
        np.testing.assert_allclose(np.asarray(xf), np.asarray(xr),
                                   atol=3e-5, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(kf), np.asarray(kr),
                                   atol=3e-5, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(vf), np.asarray(vr),
                                   atol=3e-5, rtol=1e-5)


@pytest.mark.parametrize("bits", [8, 4], ids=["int8", "int4"])
@pytest.mark.parametrize("D,F", [(32, 96), (64, 256)])
def test_mlp_kernel_parity_quantized_weights(bits, D, F):
    """Incl. the F=96 no-large-divisor tile class and an explicit even
    tile under int4 (wd packs its OUTPUT axis — the tiling proof)."""
    rng = np.random.RandomState(D + F + bits)
    dt = jnp.float32
    mk = lambda *s: jnp.asarray(rng.randn(*s) * 0.07, dt)  # noqa: E731
    x, nw = mk(3, D), jnp.asarray(rng.rand(D) + 0.5, dt)
    wg = ptq.quantize_leaf(mk(D, F), bits)
    wu = ptq.quantize_leaf(mk(D, F), bits)
    wd = ptq.quantize_leaf(mk(F, D), bits, pack_axis=1)
    got = fdb.fused_mlp_block_pallas(x, nw, wg, wu, wd)
    want = fdb.mlp_block_ref(x, nw, wg, wu, wd)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=3e-5, rtol=1e-5)
    tiled = fdb.fused_mlp_block_pallas(x, nw, wg, wu, wd,
                                       block_f=F // 2)
    np.testing.assert_allclose(np.asarray(tiled), np.asarray(want),
                               atol=3e-5, rtol=1e-5)
    if bits == 4 and F % 3 == 0:
        # an ODD F-tile is legal under int4: F is never the packed
        # axis (gate/up pack rows, down packs columns — every tile
        # fully covers the packed dim)
        odd = fdb.fused_mlp_block_pallas(x, nw, wg, wu, wd, block_f=3)
        np.testing.assert_allclose(np.asarray(odd), np.asarray(want),
                                   atol=3e-5, rtol=1e-5)


@pytest.mark.parametrize("bits", [8, 4], ids=["int8", "int4"])
def test_prefill_kernel_parity_quantized_weights(bits):
    """Warm mid-page start + ragged valid rows, quantized weights."""
    rng = np.random.RandomState(20 + bits)
    P, D, H, KV, hd, BS, MB = 16, 32, 4, 2, 16, 8, 5
    N = MB + 3
    dt = jnp.float32
    mk = lambda *s: jnp.asarray(rng.randn(*s) * 0.07, dt)  # noqa: E731
    x, nw = mk(P, D), jnp.asarray(rng.rand(D) + 0.5, dt)
    q = lambda w: ptq.quantize_leaf(w, bits)               # noqa: E731
    wq, wk, wv = q(mk(D, H * hd)), q(mk(D, KV * hd)), q(mk(D, KV * hd))
    wo = q(mk(H * hd, D))
    pos0, n_valid = 10, 13
    inv = 1.0 / (10000.0 ** (np.arange(0, hd, 2) / hd))
    ang = (pos0 + np.arange(P))[:, None] * inv[None, :]
    sin = jnp.asarray(np.sin(ang), jnp.float32)
    cos = jnp.asarray(np.cos(ang), jnp.float32)
    kp, vp = mk(N, BS, KV, hd), mk(N, BS, KV, hd)
    tab = jnp.asarray(rng.permutation(N - 1)[:MB] + 1, jnp.int32)
    args = (x, nw, wq, wk, wv, wo, sin, cos, kp, vp, tab,
            jnp.int32(pos0), jnp.int32(n_valid))
    xf, kf, vf = fpb.fused_prefill_attn_pallas(*args)
    xr, kr, vr = fpb.prefill_attn_block_ref(*args)
    np.testing.assert_allclose(np.asarray(xf[:n_valid]),
                               np.asarray(xr[:n_valid]),
                               atol=3e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(kf), np.asarray(kr),
                               atol=3e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# registry dispatch with the weight_dtype meta key
# ---------------------------------------------------------------------------
def test_flagship_dispatch_int8_and_int4():
    """Acceptance bar: BOTH quantized classes dispatch the fused
    variants on the flagship serving shape class (D=1024/H=16/hd=64)
    off interpret mode — weight quant widens the VMEM fit, never
    shrinks it."""
    for wd in ("int8", "int4"):
        meta = fdb.decode_meta_dims(8, 1024, 16, 16, 64, 4096, 16, 24,
                                    jnp.bfloat16, jnp.bfloat16, False,
                                    weight_dtype=wd)
        meta["interpret"] = False
        ok, why = fdb._supports_attn(dict(meta))
        assert ok, (wd, why)
        ok, why = fdb._supports_mlp(dict(meta))
        assert ok, (wd, why)
        from paddle_tpu.ops.pallas.registry import KERNELS
        assert KERNELS.dispatch("decode_attn_block", meta)[0] == \
            "pallas_fused"
        assert KERNELS.dispatch("decode_mlp_block", meta)[0] == \
            "pallas_fused"
        pmeta = fpb.prefill_meta_dims(64, 1024, 16, 16, 64, 4096, 16,
                                      24, jnp.bfloat16, jnp.bfloat16,
                                      False, weight_dtype=wd)
        pmeta["interpret"] = False
        assert KERNELS.dispatch("prefill_attn_block", pmeta)[0] == \
            "pallas_fused"


def test_dispatch_reason_strings_and_int4_odd_reject():
    """VMEM-fallback + packing-constraint reasons are human-readable;
    an odd hidden size cleanly rejects int4 (falls back, never packs
    garbage)."""
    meta = fdb.decode_meta_dims(2, 36, 2, 2, 20, 96, 8, 4,
                                jnp.float32, jnp.float32, False,
                                weight_dtype="int4")
    meta["interpret"] = False
    ok, why = fdb._supports_attn(dict(meta))
    assert not ok and "head_dim" in why            # hd=20 rejects first
    meta2 = fdb.decode_meta_dims(2, 33, 2, 2, 16, 96, 8, 4,
                                 jnp.float32, jnp.float32, False,
                                 weight_dtype="int4")
    meta2["interpret"] = False
    ok, why = fdb._supports_attn(dict(meta2))
    assert not ok and "even" in why and "int4" in why
    ok, why = fdb._supports_mlp(dict(meta2))
    assert not ok and "even" in why
    # the VMEM budget reason still names the budget under weight quant
    meta3 = fdb.decode_meta_dims(8, 1024, 16, 16, 64, 4096, 16, 24,
                                 jnp.bfloat16, jnp.bfloat16, False,
                                 weight_dtype="int8")
    meta3["interpret"] = False
    meta3["vmem_budget"] = 1024
    ok, why = fdb._supports_attn(dict(meta3))
    assert not ok and "VMEM" in why
    # interpret mode: auto dispatch falls back with a reason
    meta4 = fdb.decode_meta(CFG, B=2, BS=4, MB=4,
                            pool_dtype=jnp.float32, quant=False,
                            weight_dtype="int8")
    assert meta4["interpret"] and meta4["weight_dtype"] == "int8"
    _, _, names = fdb.resolve_decode_blocks(meta4, "auto")
    assert names == {"attn": "unfused", "mlp": "unfused"}


def test_weight_dtype_rides_in_declared_cache_keys():
    """The DISPATCH_KEY_GAP contract: weight_dtype is a declared cache
    key for all four serving ops (the registry lint gates the reads)."""
    from paddle_tpu.ops.pallas.registry import KERNELS
    for op in ("decode_attn_block", "decode_mlp_block",
               "prefill_attn_block", "prefill_mlp_block"):
        fields, _ = KERNELS.cache_key_decl(op)
        assert "weight_dtype" in fields, op


def test_mixed_weight_modes_rejected():
    with pytest.raises(ValueError, match="one weight-quant mode"):
        fdb.weight_dtype_of(jnp.zeros((4, 4)),
                            ptq.quantize_leaf(np.zeros((4, 4)), 8))


# ---------------------------------------------------------------------------
# step-level + engine-level routing
# ---------------------------------------------------------------------------
def _step_inputs(rng, B=2, BS=4, MB=4):
    L = CFG.num_hidden_layers
    KV, hd = CFG.num_key_value_heads, CFG.head_dim
    N = B * MB + 1
    kp = jnp.asarray(rng.randn(L, N, BS, KV, hd) * 0.1, jnp.float32)
    vp = jnp.asarray(rng.randn(L, N, BS, KV, hd) * 0.1, jnp.float32)
    tok = jnp.asarray(rng.randint(0, 97, (B,)), jnp.int32)
    bt = jnp.asarray(rng.permutation(N)[:B * MB].reshape(B, MB),
                     jnp.int32)
    lens = jnp.asarray([5, 0][:B], jnp.int32)
    return tok, kp, vp, bt, lens


@pytest.mark.parametrize("bits", [8, 4], ids=["int8", "int4"])
def test_quantized_fallback_bit_identical_to_dequant_matmul(params,
                                                            bits):
    """The acceptance contract: on CPU (dispatch -> unfused) the fused
    decode step over a quantized tree is BIT-identical to the plain
    unfused step over the same tree — both are dequantize-then-matmul
    through the one shared helper."""
    qp = ptq.quantize_weights(params, bits=bits)
    rng = np.random.RandomState(6 + bits)
    tok, kp, vp, bt, lens = _step_inputs(rng)
    lg0, kp0, vp0 = _paged_decode_step(qp, tok, CFG, kp, vp, bt, lens)
    lg1, kp1, vp1 = _fused_decode_step(qp, tok, CFG, kp, vp, bt, lens,
                                       mode="auto")
    np.testing.assert_array_equal(np.asarray(lg0), np.asarray(lg1))
    np.testing.assert_array_equal(np.asarray(kp0), np.asarray(kp1))
    # and the forced megakernel route stays roundoff-close
    lg2, _, _ = _fused_decode_step(qp, tok, CFG, kp, vp, bt, lens,
                                   mode="pallas")
    np.testing.assert_allclose(np.asarray(lg2), np.asarray(lg0),
                               atol=5e-5, rtol=1e-5)


def test_engine_stream_int8_weights(params):
    """20+-request mixed-length greedy stream on int8 weights: steady
    state stays 1 decode program + <=1 trace per bucket with zero
    retrace warnings, metrics carry the weight_quant_variant snapshot,
    and greedy output stays within a small documented flip budget vs
    the fp engine (<= 10% of tokens on these random test weights; real
    checkpoints sit far lower)."""
    rng = np.random.RandomState(7)
    specs = [(int(rng.randint(3, 15)), int(rng.randint(2, 6)))
             for _ in range(22)]
    prompts = [rng.randint(0, 97, (S,)).astype(np.int32)
               for S, _ in specs]

    def run(wq):
        eng = _engine(params, weight_quant=wq, observability=True)
        rs = [eng.submit(p, GenerationConfig(max_new_tokens=N,
                                             greedy=True))
              for p, (_, N) in zip(prompts, specs)]
        eng.drain()
        assert all(r.done for r in rs)
        return eng, [r.tokens for r in rs]

    eng_q, toks_q = run("int8")
    eng_f, toks_f = run(None)
    c = eng_q.counters
    assert c["requests_completed"] == 22
    assert c["decode_traces"] == 1, c
    assert all(n <= 1 for n in c["prefill_traces"].values()), c
    m = eng_q.metrics()
    assert m["retrace_warnings"] == 0
    assert m["weight_quant_variant"]["mode"] == "int8"
    assert m["weight_quant_variant"]["attn"] == "unfused"  # CPU route
    assert eng_f.metrics()["weight_quant_variant"] == {"mode": "off"}
    total = sum(len(t) for t in toks_f)
    flips = sum(a != b for tf, tq in zip(toks_f, toks_q)
                for a, b in zip(tf, tq))
    assert flips / total <= 0.10, (flips, total)


def test_logit_error_budget_int8(params):
    """Dense-forward logits on a fixed prompt: int8 weight quant stays
    within a small absolute budget of fp at the test shapes (the bench
    reports the same number at the bench shapes)."""
    from paddle_tpu.inference.generation import cached_forward, init_cache
    rng = np.random.RandomState(11)
    toks = jnp.asarray(rng.randint(0, 97, (1, 24)), jnp.int32)
    kc, vc = init_cache(CFG, 1, 24)
    ref = np.asarray(cached_forward(params, toks, CFG, kc, vc, 0)[0],
                     np.float32)
    qp = ptq.quantize_weights(params, bits=8)
    kc, vc = init_cache(CFG, 1, 24)
    got = np.asarray(cached_forward(qp, toks, CFG, kc, vc, 0)[0],
                     np.float32)
    err = np.abs(got - ref).max()
    spread = ref.max() - ref.min()
    assert err < 0.05 * max(spread, 1e-6), (err, spread)


def test_engine_int8_weights_with_int8_kv_cache(params):
    """Weight quant composes with the int8 KV cache (orthogonal
    quantizations: weights per-channel static, KV per-head one-shot)."""
    rng = np.random.RandomState(13)
    eng = _engine(params, weight_quant="int8", cache_dtype="int8")
    rs = [eng.submit(rng.randint(0, 97, (6,)).astype(np.int32),
                     GenerationConfig(max_new_tokens=3, greedy=True))
          for _ in range(4)]
    eng.drain()
    assert all(r.done and len(r.tokens) == 3 for r in rs)
    assert eng.counters["decode_traces"] == 1
    assert eng.counters["calibration_traces"] >= 1     # KV calibration


def test_generate_paged_weight_quant_matches_engine(params):
    """generate_paged(weight_quant=) and the engine run the same
    dequantize-then-matmul math — greedy outputs agree token for
    token."""
    rng = np.random.RandomState(15)
    prompt = rng.randint(0, 97, (8,)).astype(np.int32)
    g = GenerationConfig(max_new_tokens=5, greedy=True)
    out = np.asarray(generate_paged(params, jnp.asarray(prompt[None]),
                                    CFG, g, block_size=4,
                                    weight_quant="int8"))[0, 8:]
    eng = _engine(params, weight_quant="int8")
    r = eng.submit(prompt, g)
    eng.drain()
    np.testing.assert_array_equal(out, np.asarray(r.tokens))
    # pre-quantized trees ride as-is; a mesh is cleanly rejected
    qp = ptq.quantize_weights(params, bits=8)
    out2 = np.asarray(generate_paged(qp, jnp.asarray(prompt[None]),
                                     CFG, g, block_size=4))[0, 8:]
    np.testing.assert_array_equal(out, out2)
    with pytest.raises(ValueError, match="mesh"):
        generate_paged(params, jnp.asarray(prompt[None]), CFG, g,
                       weight_quant="int8", mesh=1)


def test_engine_rejects_tp_gt1_weight_quant(params):
    from paddle_tpu.inference import ServingMesh
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    mesh = ServingMesh.make(tp=2)
    with pytest.raises(ValueError, match="tp=2"):
        _engine(params, weight_quant="int8", mesh=mesh)


def test_disagg_weight_quant_parity(params):
    """DisaggregatedEngine threads weight_quant to both groups; greedy
    output is bit-identical to the colocated quantized engine."""
    from paddle_tpu.inference.disagg import DisaggregatedEngine
    rng = np.random.RandomState(17)
    prompts = [rng.randint(0, 97, (int(rng.randint(3, 12)),))
               .astype(np.int32) for _ in range(6)]
    g = GenerationConfig(max_new_tokens=4, greedy=True)
    devs = jax.devices()
    eng = DisaggregatedEngine(params, CFG, capacity=2, prefill_slots=1,
                              block_size=4, max_seq_len=64,
                              prefill_buckets=(16,),
                              # tp=1 groups (the quantized-tree
                              # contract; multi-chip groups reject)
                              prefill_devices=devs[:1],
                              decode_devices=devs[1:2] or devs[:1],
                              weight_quant="int8")
    rs = [eng.submit(p, g) for p in prompts]
    eng.drain()
    co = _engine(params, capacity=2, block_size=4, max_seq_len=64,
                 prefill_buckets=(16,), weight_quant="int8")
    rs2 = [co.submit(p, g) for p in prompts]
    co.drain()
    assert [r.tokens for r in rs] == [r.tokens for r in rs2]
    m = eng.metrics()
    assert m["groups"]["decode"]["weight_quant_variant"]["mode"] == \
        "int8"


def test_audit_clean_for_wq_program(params):
    """The quantized-weight engine's programs audit clean (the
    serving_decode_wq catalog entry rides the same hook)."""
    eng = _engine(params, weight_quant="int8")
    reports = eng.audit(register=False)
    bad = [f for r in reports for f in r.findings
           if f.severity in ("error", "warning")]
    assert not bad, bad
