"""Quantization (QAT/PTQ) + ASP 2:4 sparsity tests (reference test model:
test/quantization/ + test/asp/)."""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (QuantConfig, QAT, PTQ, QuantedLinear,
                                     Int8Linear, AbsmaxObserver,
                                     MovingAverageAbsmaxObserver,
                                     PerChannelAbsmaxObserver, fake_quant,
                                     quantize_to_int8, int8_matmul)
from paddle_tpu.incubate import asp


# -- observers --------------------------------------------------------------
def test_absmax_observer_scale():
    ob = AbsmaxObserver(8)
    ob.observe(np.array([1.0, -3.0, 2.0]))
    ob.observe(np.array([0.5, -1.0]))
    assert ob.scale() == pytest.approx(3.0 / 127.0)


def test_per_channel_observer():
    ob = PerChannelAbsmaxObserver(8, axis=-1)
    ob.observe(np.array([[1.0, -4.0], [2.0, 0.5]]))
    np.testing.assert_allclose(ob.scale(),
                               np.array([2.0, 4.0]) / 127.0, rtol=1e-6)


def test_moving_average_observer():
    ob = MovingAverageAbsmaxObserver(8, momentum=0.5)
    ob.observe(np.array([2.0]))
    ob.observe(np.array([4.0]))
    assert ob.scale() == pytest.approx(3.0 / 127.0)   # 0.5*2 + 0.5*4


# -- fake quant / int8 ------------------------------------------------------
def test_fake_quant_roundtrip_error_bound():
    x = paddle.to_tensor(np.linspace(-1, 1, 256).astype(np.float32))
    scale = 1.0 / 127.0
    q = fake_quant(x, scale)
    err = np.abs(q.numpy() - x.numpy())
    assert err.max() <= scale / 2 + 1e-6


def test_fake_quant_ste_gradient():
    x = paddle.to_tensor(np.array([0.3, -0.7], np.float32))
    x.stop_gradient = False
    y = fake_quant(x, 1.0 / 127.0)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones(2), rtol=1e-6)


def test_int8_matmul_close_to_fp():
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    w = rng.randn(16, 4).astype(np.float32)
    w_q, w_scale = quantize_to_int8(w, axis=-1)
    x_scale = np.abs(x).max() / 127.0
    x_q = np.clip(np.round(x / x_scale), -128, 127).astype(np.int8)
    out = int8_matmul(jnp.asarray(x_q), jnp.asarray(w_q), x_scale,
                      w_scale.reshape(-1))
    ref = x @ w
    rel = np.abs(np.asarray(out) - ref).max() / np.abs(ref).max()
    assert rel < 0.05


# -- QAT --------------------------------------------------------------------
def _mlp():
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


@pytest.mark.slow
def test_qat_quantize_swaps_and_trains():
    net = _mlp()
    qat = QAT(QuantConfig())
    net = qat.quantize(net)
    assert isinstance(net[0], QuantedLinear)
    assert isinstance(net[2], QuantedLinear)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                         .astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randn(4, 4)
                         .astype(np.float32))
    opt = paddle.optimizer.SGD(0.05, parameters=net.parameters())
    losses = []
    for _ in range(15):
        loss = nn.functional.mse_loss(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_qat_convert_to_int8_close():
    net = _mlp()
    qat = QAT(QuantConfig())
    qnet = qat.quantize(net)
    x = paddle.to_tensor(np.random.RandomState(0).randn(16, 8)
                         .astype(np.float32))
    with paddle.no_grad():
        qnet.train()
        _ = qnet(x)          # calibrate activation observers (train mode)
        qnet.eval()
        ref = qnet(x).numpy()
        # eval mode must NOT mutate calibration stats (regression)
        s0 = float(qnet[0].act_quanter.observer.scale())
        _ = qnet(x * 100.0)
        assert float(qnet[0].act_quanter.observer.scale()) == s0
    inet = qat.convert(qnet)
    assert isinstance(inet[0], Int8Linear)
    with paddle.no_grad():
        out = inet(x).numpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.1


# -- PTQ --------------------------------------------------------------------
def test_ptq_calibrate_and_convert():
    net = _mlp()
    net.eval()
    with paddle.no_grad():
        x = paddle.to_tensor(np.random.RandomState(0).randn(32, 8)
                             .astype(np.float32))
        ref = net(x).numpy()
        ptq = PTQ()
        onet = ptq.quantize(net, inplace=False)
        for i in range(4):
            _ = onet(x)
        inet = ptq.convert(onet)
        out = inet(x).numpy()
    assert isinstance(inet[0], Int8Linear)
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.1


# -- quantized serving (VERDICT r4 Next #9) ---------------------------------
def test_ptq_int8_through_predictor_on_zoo_model(tmp_path):
    """The reference ships int8 end-to-end through slim + TensorRT
    (paddle/fluid/inference/tensorrt/convert/); our analog: PTQ-calibrate
    a zoo model, convert its Linears to Int8Linear, jit.save the
    quantized net, serve it through the Predictor, and bound the
    accuracy delta against the float predictor."""
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.static import InputSpec
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet(num_classes=10)
    net.eval()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 1, 28, 28).astype(np.float32))

    with paddle.no_grad():
        ref = net(x).numpy()
        ptq = PTQ()
        onet = ptq.quantize(net, inplace=False)
        for _ in range(4):
            onet(paddle.to_tensor(
                rng.randn(8, 1, 28, 28).astype(np.float32)))
        inet = ptq.convert(onet)

    spec = [InputSpec([8, 1, 28, 28], "float32")]
    fpath = str(tmp_path / "float")
    qpath = str(tmp_path / "int8")
    paddle.jit.save(net, fpath, input_spec=spec)
    paddle.jit.save(inet, qpath, input_spec=spec)

    out_f = create_predictor(Config(fpath)).run([x])[0].numpy()
    out_q = create_predictor(Config(qpath)).run([x])[0].numpy()
    np.testing.assert_allclose(out_f, ref, rtol=1e-4, atol=1e-4)
    # accuracy delta: int8 predictions track float within a few percent
    # of the logit range, and the argmax (the served answer) agrees
    rel = np.abs(out_q - out_f).max() / (np.abs(out_f).max() + 1e-9)
    assert rel < 0.1, f"int8 serving degraded: rel={rel}"
    # argmax must agree wherever the float decision is decisive (top-2
    # margin above the int8 noise floor); near-ties may legally flip
    top2 = np.sort(out_f, axis=-1)[:, -2:]
    margin = top2[:, 1] - top2[:, 0]
    decisive = margin > 2 * np.abs(out_q - out_f).max()
    agree = (out_q.argmax(-1) == out_f.argmax(-1))[decisive]
    assert decisive.sum() == 0 or agree.all(), \
        f"decisive argmax flipped: {agree}"


def test_ptq_fp8_through_predictor(tmp_path):
    """FP8 deploy path: PTQ convert(target='fp8') swaps Linears for
    FP8Linear (e4m3 weights, MXU gemm, fp32 accumulate) and the result
    serves through jit.save -> Predictor."""
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.quantization import FP8Linear
    from paddle_tpu.static import InputSpec

    net = _mlp()
    net.eval()
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
    with paddle.no_grad():
        ref = net(x).numpy()
        ptq = PTQ()
        onet = ptq.quantize(net, inplace=False)
        onet(x)
        fnet = ptq.convert(onet, target="fp8")
        assert isinstance(fnet[0], FP8Linear)
        out_eager = fnet(x).numpy()
    rel = np.abs(out_eager - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.15, f"fp8 forward degraded: rel={rel}"

    path = str(tmp_path / "fp8")
    paddle.jit.save(fnet, path, input_spec=[InputSpec([16, 8], "float32")])
    out_pred = create_predictor(Config(path)).run([x])[0].numpy()
    np.testing.assert_allclose(out_pred, out_eager, rtol=1e-3, atol=1e-4)


# -- ASP 2:4 ----------------------------------------------------------------
def test_create_mask_2_4_pattern():
    w = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    mask = asp.create_mask(w, 2, 4)
    assert mask.shape == w.shape
    groups = mask.reshape(-1, 4).sum(axis=1)
    assert (groups == 2).all()
    # keeps the largest two per group
    g0 = np.abs(w.reshape(-1, 4)[0])
    kept = np.where(mask.reshape(-1, 4)[0])[0]
    assert set(kept) == set(np.argsort(-g0)[:2])


def test_check_mask_1d():
    ok = np.array([[1, 0, 2, 0], [0, 3, 0, 4]], np.float32)
    bad = np.array([[1, 2, 3, 0]], np.float32)
    assert asp.check_mask_1d(ok, 2, 4)
    assert not asp.check_mask_1d(bad, 2, 4)


def test_decorate_before_prune_still_enforces_masks():
    """Regression: the reference's documented order is decorate() first,
    prune_model() second — masks must still be re-applied."""
    net = _mlp()
    opt = asp.decorate(paddle.optimizer.SGD(0.1,
                                            parameters=net.parameters()),
                       model=net)
    asp.prune_model(net, 2, 4)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                         .astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randn(4, 4)
                         .astype(np.float32))
    for _ in range(2):
        loss = nn.functional.mse_loss(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    for _, layer in net.named_sublayers():
        if isinstance(layer, nn.Linear):
            assert asp.check_mask_1d(layer.weight.numpy(), 2, 4)


def test_prune_model_and_decorated_optimizer_keeps_sparsity():
    net = _mlp()
    masks = asp.prune_model(net, 2, 4)
    assert len(masks) == 2
    for _, layer in net.named_sublayers():
        if isinstance(layer, nn.Linear):
            assert asp.check_mask_1d(layer.weight.numpy(), 2, 4)
    opt = asp.decorate(paddle.optimizer.SGD(0.1,
                                            parameters=net.parameters()),
                       model=net)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                         .astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randn(4, 4)
                         .astype(np.float32))
    for _ in range(3):
        loss = nn.functional.mse_loss(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    for _, layer in net.named_sublayers():
        if isinstance(layer, nn.Linear):
            assert asp.check_mask_1d(layer.weight.numpy(), 2, 4)


# -- nn.quant weight-only serving (reference: nn/quant/quantized_linear.py)
class TestWeightOnlyQuant:
    def test_int8_roundtrip_and_linear(self):
        from paddle_tpu.nn.quant import (weight_dequantize,
                                         weight_only_linear,
                                         weight_quantize)
        rng = np.random.RandomState(0)
        w = paddle.to_tensor(rng.randn(64, 32).astype(np.float32))
        q, s = weight_quantize(w, algo="weight_only_int8")
        assert tuple(q.shape) == (32, 64)      # transposed, like the ref
        assert tuple(s.shape) == (32,)
        wd = weight_dequantize(q, s, out_dtype="float32")
        rel = np.abs(wd.numpy() - w.numpy()).max() / np.abs(
            w.numpy()).max()
        assert rel < 0.01                      # 1/127 rounding class
        x = paddle.to_tensor(rng.randn(4, 64).astype(np.float32))
        b = paddle.to_tensor(rng.randn(32).astype(np.float32))
        out = weight_only_linear(x, q, bias=b, weight_scale=s)
        ref = x.numpy() @ w.numpy() + b.numpy()
        rel = np.abs(out.numpy() - ref).max() / np.abs(ref).max()
        assert rel < 0.02

    def test_int4_pack_roundtrip_and_linear(self):
        from paddle_tpu.nn.quant import (weight_dequantize,
                                         weight_only_linear,
                                         weight_quantize)
        rng = np.random.RandomState(1)
        w = paddle.to_tensor(rng.randn(64, 16).astype(np.float32))
        q, s = weight_quantize(w, algo="weight_only_int4")
        assert tuple(q.shape) == (16, 32)      # two nibbles per byte
        wd = weight_dequantize(q, s, algo="weight_only_int4",
                               out_dtype="float32")
        rel = np.abs(wd.numpy() - w.numpy()).max() / np.abs(
            w.numpy()).max()
        assert rel < 0.16                      # 1/7 rounding class
        x = paddle.to_tensor(rng.randn(2, 3, 64).astype(np.float32))
        out = weight_only_linear(x, q, weight_scale=s,
                                 weight_dtype="int4")
        ref = x.numpy() @ w.numpy()
        assert out.shape == [2, 3, 16]
        rel = np.abs(out.numpy() - ref).max() / np.abs(ref).max()
        assert rel < 0.2

    def test_grouped_scales(self):
        from paddle_tpu.nn.quant import (weight_dequantize,
                                         weight_quantize)
        rng = np.random.RandomState(2)
        # per-group scales must beat per-channel when one group is huge
        w_np = rng.randn(128, 8).astype(np.float32)
        w_np[:64] *= 100.0
        w = paddle.to_tensor(w_np)
        q_pc, s_pc = weight_quantize(w)
        q_g, s_g = weight_quantize(w, group_size=64)
        assert tuple(s_g.shape) == (2, 8)
        err_pc = np.abs(weight_dequantize(q_pc, s_pc,
                                          out_dtype="float32").numpy()
                        - w_np)[64:].max()
        err_g = np.abs(weight_dequantize(q_g, s_g, group_size=64,
                                         out_dtype="float32").numpy()
                       - w_np)[64:].max()
        assert err_g < err_pc * 0.1

    def test_llm_int8_outlier_decomposition(self):
        from paddle_tpu.nn.quant import llm_int8_linear, weight_quantize
        rng = np.random.RandomState(3)
        w = paddle.to_tensor(rng.randn(32, 16).astype(np.float32))
        q, s = weight_quantize(w, algo="llm.int8")
        x_np = rng.randn(8, 32).astype(np.float32)
        x_np[:, 5] *= 50.0                     # one outlier feature
        x = paddle.to_tensor(x_np)
        out = llm_int8_linear(x, q, weight_scale=s, threshold=6.0)
        ref = x_np @ np.asarray(w.numpy())
        rel = np.abs(out.numpy() - ref).max() / np.abs(ref).max()
        assert rel < 0.03                      # outliers exact-ish in fp
        # naive full-int8 activation quant would be much worse here
        from paddle_tpu.quantization import int8_matmul
        a_s = np.abs(x_np).max() / 127.0
        xq = np.clip(np.round(x_np / a_s), -127, 127).astype(np.int8)
        naive = np.asarray(int8_matmul(
            jnp.asarray(xq), jnp.asarray(np.asarray(q.numpy())).T, a_s,
            jnp.asarray(np.asarray(s.numpy()))))
        rel_naive = np.abs(naive - ref).max() / np.abs(ref).max()
        assert rel < rel_naive

    def test_apply_per_channel_scale_and_validation(self):
        from paddle_tpu.nn.quant import (apply_per_channel_scale,
                                         weight_quantize)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        s = paddle.to_tensor(np.asarray([1, 2, 3, 4], np.float32))
        out = apply_per_channel_scale(x, s)
        np.testing.assert_allclose(out.numpy(),
                                   [[1, 2, 3, 4]] * 2)
        with pytest.raises(ValueError):
            weight_quantize(x, algo="nope")
        with pytest.raises(ValueError):
            weight_quantize(x, group_size=32)
