"""Activation checkpointing (reference:
python/paddle/distributed/fleet/recompute/recompute.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet import recompute


class TestRecompute:
    def _net(self, seed=0):
        paddle.seed(seed)
        return nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                             nn.Linear(16, 8))

    def test_grads_match_plain(self):
        net = self._net()
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                             .astype(np.float32), stop_gradient=False)
        out = recompute(net, x)
        out.mean().backward()
        g_rc = [np.asarray(p.grad.numpy()) for p in net.parameters()]
        gx_rc = np.asarray(x.grad.numpy())

        net2 = self._net()
        x2 = paddle.to_tensor(np.asarray(x.numpy()), stop_gradient=False)
        net2(x2).mean().backward()
        g_pl = [np.asarray(p.grad.numpy()) for p in net2.parameters()]
        for a, b in zip(g_rc, g_pl):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(gx_rc, np.asarray(x2.grad.numpy()),
                                   rtol=1e-5, atol=1e-6)

    def test_forward_value_matches(self):
        net = self._net(3)
        x = paddle.to_tensor(np.random.RandomState(1).randn(2, 8)
                             .astype(np.float32))
        np.testing.assert_allclose(np.asarray(recompute(net, x).numpy()),
                                   np.asarray(net(x).numpy()),
                                   rtol=1e-6)

    def test_capture_cache_hit(self):
        from paddle_tpu.distributed.fleet.utils import _CAPTURE_CACHE
        net = self._net(5)
        x = paddle.to_tensor(np.random.RandomState(2).randn(2, 8)
                             .astype(np.float32))
        before = len(_CAPTURE_CACHE)
        recompute(net, x)
        assert len(_CAPTURE_CACHE) == before + 1
        recompute(net, x)   # same function + shapes: no new entry
        assert len(_CAPTURE_CACHE) == before + 1

    def test_trains_in_loop(self):
        net = self._net(7)
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        rng = np.random.RandomState(0)
        X = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
        Y = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
        losses = []
        for _ in range(10):
            out = recompute(net, X)
            loss = ((out - Y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_kwargs_passthrough(self):
        def seg(a, scale=1.0):
            return a * scale
        x = paddle.to_tensor(np.ones(3, np.float32))
        out = recompute(seg, x, scale=2.0)
        np.testing.assert_allclose(np.asarray(out.numpy()), [2, 2, 2])


class TestRecomputeReviewRegressions:
    def test_non_tensor_args_pass_through(self):
        """Python-typed args must reach the segment untouched."""
        def seg(x, n, mode):
            assert isinstance(n, int) and mode == "double"
            for _ in range(n):
                x = x * 2.0
            return x
        x = paddle.to_tensor(np.ones(3, np.float32))
        out = recompute(seg, x, 2, "double")
        np.testing.assert_allclose(np.asarray(out.numpy()), [4, 4, 4])

    def test_closure_reading_arg_tensor_not_baked(self):
        """A closure that reads the SAME tensor passed positionally must
        see the traced operand: d/dx (x + x) == 2, not 1."""
        x = paddle.to_tensor(np.float32(3.0), stop_gradient=False)
        out = recompute(lambda a: a + x, x)
        out.backward()
        assert float(x.grad.numpy()) == 2.0

    def test_ephemeral_functions_no_stale_cache(self):
        """Two different models through ephemeral callables must each
        get their own gradients (id-reuse must not alias cache
        entries)."""
        import gc
        grads = []
        for seed in (1, 2):
            net = nn.Linear(4, 4)
            paddle.seed(seed)
            x = paddle.to_tensor(np.ones((2, 4), np.float32))
            out = recompute(lambda v: net(v) * 1.0, x)
            out.sum().backward()
            grads.append(np.asarray(net.weight.grad.numpy()).copy())
            assert np.abs(grads[-1]).sum() > 0
            del net
            gc.collect()

    def test_cache_dies_with_function(self):
        import gc
        from paddle_tpu.distributed.fleet.utils import _CAPTURE_CACHE
        net = nn.Linear(4, 4)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        recompute(net, x)
        assert net in _CAPTURE_CACHE
        n_before = len(_CAPTURE_CACHE)
        del net
        gc.collect()
        assert len(_CAPTURE_CACHE) < n_before   # weak key released

    def test_tensor_kwarg_grad_on_cache_hit(self):
        """Review regression: a Tensor kwarg must get gradients on the
        SECOND (cache-hit) call, not just the first."""
        def seg(a, w=None):
            return (a * w).sum()
        x1 = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        k1 = paddle.to_tensor(np.full(3, 2.0, np.float32),
                              stop_gradient=False)
        recompute(seg, x1, w=k1).backward()
        np.testing.assert_allclose(np.asarray(k1.grad.numpy()), [1, 1, 1])
        x2 = paddle.to_tensor(np.full(3, 5.0, np.float32),
                              stop_gradient=False)
        k2 = paddle.to_tensor(np.full(3, 3.0, np.float32),
                              stop_gradient=False)
        out2 = recompute(seg, x2, w=k2)
        assert float(out2.numpy()) == 45.0
        out2.backward()
        assert k2.grad is not None
        np.testing.assert_allclose(np.asarray(k2.grad.numpy()), [5, 5, 5])
        np.testing.assert_allclose(np.asarray(x2.grad.numpy()), [3, 3, 3])
