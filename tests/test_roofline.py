"""Kernel roofline observatory: modeled bytes/FLOPs per launch
(hand-checked against the captured geometry), the FLOP-formula
registry's full-coverage contract, the roofline classification math,
the per-decode-variant step model, peak-table source labelling, the
trace_summary CLI's roofline readout + error handling, and the
kernel_bench_gate roofline mode incl. its --demo-regression
self-check."""
import json
import os
import subprocess
import sys

import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.roofline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SUMMARY_CLI = os.path.join(REPO, "tools", "trace_summary.py")
GATE_CLI = os.path.join(REPO, "tools", "kernel_bench_gate.py")

from paddle_tpu.analysis.kernel_catalog import (ALL_KERNEL_NAMES,  # noqa: E402
                                                FLOP_FORMULAS,
                                                flop_formula_findings,
                                                modeled_flops)
from paddle_tpu.analysis.kernel_rules import modeled_launch_bytes  # noqa: E402
from paddle_tpu.observability.compile import (device_peak_flops,   # noqa: E402
                                              device_peak_hbm_bw)
from paddle_tpu.observability.roofline import (capture_kernel_costs,  # noqa: E402
                                               decode_roofline,
                                               decode_step_bytes,
                                               kernel_cost,
                                               roofline_chrome_events,
                                               roofline_point)
from paddle_tpu.ops.pallas._util import capture_kernel_launches    # noqa: E402


def _cli(path, *args, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(env_extra or {}))
    return subprocess.run([sys.executable, path, *args],
                          capture_output=True, text=True, env=env,
                          timeout=600)


# -- FLOP formula coverage ---------------------------------------------


def test_flop_formula_full_coverage():
    """Every audited kernel name has a registered formula — the
    COVERAGE_GAP analogue: a new kernel without one is a finding, not
    a silent hole in the roofline."""
    assert set(ALL_KERNEL_NAMES) <= set(FLOP_FORMULAS)
    assert flop_formula_findings() == []


# -- hand-checked bytes/FLOPs ------------------------------------------


def test_paged_decode_bytes_flops_hand_checked():
    """Streamed-operand model, pinned geometry (pages_per_step=1,
    B=2, H=4, KV=2, hd=16, BS=8, MB=4, f32):

    - q [2,4,16]: one (1,4,16) block per batch row -> 2 x 256 B
    - k/v pools: the grid walks B*MB=8 DISTINCT pages (the full
      prefetch probe defeats the page-index length clamp) ->
      8 x (8*2*16*4) = 8192 B each
    - out [2,4,16]: 2 x 256 B

    total 17408 B; FLOPs = 4*B*H*hd*MB*BS = 16384 (QK^T + PV over the
    full table)."""
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_attention_decode_pallas)
    B, H, KV, hd, BS, NP, MB = 2, 4, 2, 16, 8, 8, 4
    q = jnp.zeros((B, H, hd), jnp.float32)
    pool = jnp.zeros((NP, BS, KV, hd), jnp.float32)
    bt = jnp.zeros((B, MB), jnp.int32)
    ln = jnp.zeros((B,), jnp.int32)
    with capture_kernel_launches() as specs:
        jax.eval_shape(
            lambda *a: paged_attention_decode_pallas(
                *a, pages_per_step=1), q, pool, pool, bt, ln)
    (spec,) = specs
    assert spec.name == "paged_attention_decode"
    bm = modeled_launch_bytes(spec)
    assert bm["total_bytes"] == 512 + 8192 + 8192 + 512 == 17408
    assert bm["read_bytes"] == 17408 - 512
    assert bm["written_bytes"] == 512
    assert modeled_flops(spec) == 4 * B * H * hd * MB * BS == 16384


def test_decode_block_fused_bytes_flops_hand_checked():
    """Resident + streamed split, pinned geometry (pages_per_step=1,
    block_f=32; B=2, D=32, H=KV=2, hd=16, F=64, BS=8, MB=4, f32; the
    grid is (B, MB/pp + F/block_f) = (2, 6)):

    - x: 2 x 128 B = 256 (one (1, D) row block per batch step)
    - norm weights nw/pw: resident once, 128 B each
    - attn weights wq/wk/wv/wo [32,32]: RESIDENT once, 4096 B each
      (constant index map -> revisit-elided)
    - MLP weights wg/wu/wd: blocked (.., 32), re-streamed per batch
      row -> B * F/block_f = 4 fetches x 4096 B = 16384 B each
    - sin/cos [2,8]: 2 x 32 B = 64 each
    - k/v pools: 8 distinct pages x 1024 B = 8192 each
    - outs x_out/k_new/v_new: 2 x 128 B = 256 each

    total 83328 B; FLOPs = B*(8D + 2*D*Hhd + 4*D*KVhd + 2*Hhd*D
    + 4*Hhd*MB*BS + 6*D*F + 4F) = 50176."""
    from paddle_tpu.ops.pallas.fused_decode_block import (
        fused_decode_block_pallas)
    B, D, H, KV, hd, F, BS, NP, MB = 2, 32, 2, 2, 16, 64, 8, 8, 4
    f32 = jnp.float32
    x = jnp.zeros((B, D), f32)
    nw = jnp.zeros((D,), f32)
    pw = jnp.zeros((D,), f32)
    wq = jnp.zeros((D, H * hd), f32)
    wk = jnp.zeros((D, KV * hd), f32)
    wv = jnp.zeros((D, KV * hd), f32)
    wo = jnp.zeros((H * hd, D), f32)
    wg = jnp.zeros((D, F), f32)
    wu = jnp.zeros((D, F), f32)
    wd = jnp.zeros((F, D), f32)
    sin = jnp.zeros((BS * MB, hd // 2), f32)
    cos = jnp.zeros((BS * MB, hd // 2), f32)
    pool = jnp.zeros((NP, BS, KV, hd), f32)
    bt = jnp.zeros((B, MB), jnp.int32)
    ln = jnp.zeros((B,), jnp.int32)
    with capture_kernel_launches() as specs:
        jax.eval_shape(
            lambda *a: fused_decode_block_pallas(
                *a, pages_per_step=1, block_f=32),
            x, nw, wq, wk, wv, wo, pw, wg, wu, wd, sin, cos,
            pool, pool, bt, ln)
    (spec,) = specs
    assert spec.name == "decode_block_fused"
    assert tuple(spec.grid) == (2, 6)
    bm = modeled_launch_bytes(spec)
    expected = (256            # x, streamed per batch row
                + 2 * 128      # nw + pw, resident
                + 4 * 4096     # wq/wk/wv/wo, resident once
                + 3 * 16384    # wg/wu/wd, re-streamed per batch row
                + 2 * 64       # sin/cos
                + 2 * 8192     # k/v pools, 8 distinct pages
                + 3 * 256)     # x_out, k_new, v_new
    assert bm["total_bytes"] == expected == 83328
    Hhd, KVhd = H * hd, KV * hd
    assert modeled_flops(spec) == B * (
        8 * D + 2 * D * Hhd + 4 * D * KVhd + 2 * Hhd * D
        + 4 * Hhd * MB * BS + 6 * D * F + 4 * F) == 50176


def test_capture_kernel_costs_end_to_end():
    from paddle_tpu.ops.pallas.norms import rms_norm_pallas
    x = jnp.zeros((24, 128), jnp.float32)
    w = jnp.zeros((128,), jnp.float32)
    rows = capture_kernel_costs(rms_norm_pallas, x, w,
                                times_us={"rms_norm_fwd": 10.0})
    (row,) = rows
    assert row["kernel"] == "rms_norm_fwd"
    assert row["flops_model"] == "formula"
    assert row["bytes_modeled"] > 0
    assert row["bound"] == "memory"       # norms sit far left of ridge
    assert row["achieved_bw_frac"] is not None


# -- roofline classification math --------------------------------------


def test_roofline_point_bounds_and_fractions():
    peaks = {"peak_flops": 100e12, "peak_hbm_bw": 1e12,
             "peak_source": {"flops": "test", "hbm_bw": "test"}}
    # ridge = 100 FLOP/B: intensity 10 -> memory bound
    p = roofline_point(1e9, 1e10, peaks=peaks)
    assert p["intensity"] == 10.0 and p["bound"] == "memory"
    # bytes bound: 1e9 B / 1e12 B/s = 1000 us (>> 100 us compute side)
    assert p["time_at_roofline_us"] == 1000.0
    assert p["achieved_bw_frac"] is None   # no measured time
    # measured at 2x the floor -> 50% of peak BW, 50% of roofline
    p = roofline_point(1e9, 1e10, time_us=2000.0, peaks=peaks)
    assert p["achieved_bw_frac"] == 0.5
    assert p["roofline_frac"] == 0.5
    assert p["achieved_flops_frac"] == 0.05
    # intensity 1000 -> compute bound
    p = roofline_point(1e7, 1e10, peaks=peaks)
    assert p["bound"] == "compute"
    # missing inputs stay None, never zero
    p = roofline_point(None, None, time_us=5.0, peaks=peaks)
    assert p["intensity"] is None and p["bound"] is None
    assert p["achieved_bw_frac"] is None


def test_decode_step_bytes_closed_forms():
    B, D, H, KV, hd, F, BS, MB = 4, 64, 4, 2, 16, 128, 8, 4
    sb = decode_step_bytes(B, D, H, KV, hd, F, BS, MB,
                           act_itemsize=2, weight_itemsize=2,
                           pool_itemsize=2)
    Hhd, KVhd = H * hd, KV * hd
    w_attn = (D * Hhd + 2 * D * KVhd + Hhd * D) * 2
    w_mlp = 3 * D * F * 2
    kv = 2 * B * MB * BS * KVhd * 2
    x = B * D * 2
    assert sb["pallas_block"] == w_attn + B * w_mlp + kv + 2 * x
    assert sb["pallas_fused"] == w_attn + w_mlp + kv + 4 * x
    assert sb["unfused"] == w_attn + w_mlp + kv + 10 * x \
        + 6 * B * F * 2
    # int8 weights shrink only the weight terms
    sb8 = decode_step_bytes(B, D, H, KV, hd, F, BS, MB,
                            weight_itemsize=1)
    assert sb8["pallas_fused"] == w_attn // 2 + w_mlp // 2 + kv + 4 * x


def test_decode_roofline_and_chrome_events():
    peaks = {"peak_flops": 100e12, "peak_hbm_bw": 1e12,
             "peak_source": {"flops": "test", "hbm_bw": "test"}}
    rep = decode_roofline({"pallas_fused": 1_000_000},
                          measured_us={"pallas_fused": 2.0},
                          peaks=peaks)
    row = rep["variants"]["pallas_fused"]
    assert row["step_us_at_peak_bw"] == 1.0    # 1 MB / 1 TB/s
    assert row["achieved_bw_frac"] == 0.5
    rep2 = decode_roofline({"unfused": 500}, peaks=peaks)
    assert rep2["variants"]["unfused"]["achieved_bw_frac"] is None
    events = roofline_chrome_events(rep)
    assert events == [{"name": "roofline:pallas_fused", "ph": "C",
                       "ts": 0.0,
                       "args": {"bytes_per_step": 1_000_000}}]


# -- peak table source labelling ---------------------------------------


def test_peak_source_labels(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_PEAK_HBM_BW", raising=False)
    monkeypatch.delenv("PADDLE_TPU_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
    bw, src = device_peak_hbm_bw()
    assert (bw, src) == (819e9, "default:v5e")
    fl, fsrc = device_peak_flops()
    assert (fl, fsrc) == (197e12, "default:v5e")
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5p-8")
    assert device_peak_hbm_bw() == (2765e9, "gen:v5p")
    assert device_peak_flops() == (459e12, "gen:v5p")
    monkeypatch.setenv("PADDLE_TPU_PEAK_HBM_BW", "1.5e12")
    assert device_peak_hbm_bw() == (1.5e12, "env")


# -- trace_summary CLI: roofline readout + robust load ------------------


def _write_timeline(path, roofline=True):
    meta = {"kind": "meta", "schema": 1, "mode": "serving"}
    if roofline:
        meta["roofline"] = {
            "variants": {"unfused": {"bytes_per_step": 424192,
                                     "step_us_at_peak_bw": 0.518,
                                     "achieved_bw_frac": None}},
            "peak_hbm_bw": 819e9,
            "peak_source": {"flops": "default:v5e",
                            "hbm_bw": "default:v5e"}}
    with open(path, "w") as f:
        f.write(json.dumps(meta) + "\n")
        for i in range(4):
            f.write(json.dumps(
                {"kind": "event", "name": "decode_step",
                 "t": 0.001 * i, "dur_ms": 2.0,
                 "decode_variant": "unfused"}) + "\n")


def test_trace_summary_roofline_readout(tmp_path):
    p = tmp_path / "t.jsonl"
    _write_timeline(str(p))
    r = _cli(SUMMARY_CLI, str(p), "--mode", "serving")
    assert r.returncode == 0, r.stderr
    assert "us measured," in r.stdout
    assert "us at peak BW" in r.stdout
    assert "of roofline" in r.stdout
    r = _cli(SUMMARY_CLI, str(p), "--mode", "serving", "--json")
    dec = json.loads(r.stdout)["decode"]
    row = dec["variants"]["unfused"]
    assert row["step_us_at_peak_bw"] == 0.518
    assert row["bytes_per_step_modeled"] == 424192
    # 2000 us measured vs 0.518 us floor (rounded to 4 decimals)
    assert row["roofline_frac"] == pytest.approx(0.518 / 2000, abs=1e-4)


def test_trace_summary_error_paths(tmp_path):
    # missing file: one-line error, nonzero, no traceback
    r = _cli(SUMMARY_CLI, str(tmp_path / "nope.jsonl"))
    assert r.returncode == 2
    assert "cannot read timeline file" in r.stderr
    assert "Traceback" not in r.stderr
    # empty file
    p = tmp_path / "empty.jsonl"
    p.write_text("")
    r = _cli(SUMMARY_CLI, str(p))
    assert r.returncode == 2
    assert "empty timeline file" in r.stderr
    assert "Traceback" not in r.stderr
    # truncated JSON (no parseable records at all)
    p = tmp_path / "trunc.jsonl"
    p.write_text('{"kind": "meta", "sch')
    r = _cli(SUMMARY_CLI, str(p))
    assert r.returncode == 2
    assert "no parseable timeline records" in r.stderr
    assert "Traceback" not in r.stderr


# -- kernel_bench_gate --roofline --------------------------------------


def _bank(tmp_path, fracs):
    doc = {"parsed": {"kernels": {"interpret": False, "cases": {
        k: {"ok": True, "us_pallas": 100.0, "achieved_bw_frac": v}
        for k, v in fracs.items()}}}}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(doc))


def _capture(tmp_path, fracs, name="fresh.json"):
    doc = {"kernels": {"interpret": False, "cases": {
        k: {"ok": True, "us_pallas": 100.0, "achieved_bw_frac": v}
        for k, v in fracs.items()}}}
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_roofline_gate_clean_and_regressed(tmp_path):
    _bank(tmp_path, {"paged_decode": 0.60})
    ok = _capture(tmp_path, {"paged_decode": 0.55}, "ok.json")
    r = _cli(GATE_CLI, "--capture", ok, "--roofline",
             "--repo", str(tmp_path))
    assert r.returncode == 0, r.stderr
    bad = _capture(tmp_path, {"paged_decode": 0.10}, "bad.json")
    r = _cli(GATE_CLI, "--capture", bad, "--roofline",
             "--repo", str(tmp_path))
    assert r.returncode == 1
    assert "ROOFLINE REGRESSION" in r.stderr


def test_roofline_gate_skip_semantics(tmp_path):
    # no banked roofline data -> SKIP (exit 0), same as the timing gate
    cap = _capture(tmp_path, {"paged_decode": 0.5})
    r = _cli(GATE_CLI, "--capture", cap, "--roofline",
             "--repo", str(tmp_path / "nothing"))
    assert r.returncode == 0
    assert "SKIP" in r.stdout


def test_roofline_gate_demo_regression():
    """The injected bandwidth collapse MUST fail the gate — end-to-end
    proof the roofline wiring can actually reject."""
    r = _cli(GATE_CLI, "--demo-regression")
    assert r.returncode == 1
    assert "ROOFLINE REGRESSION" in r.stderr
    # and it refuses to shadow a real capture
    r = _cli(GATE_CLI, "--demo-regression", "--capture", "x.json")
    assert r.returncode == 3
