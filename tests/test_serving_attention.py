"""Serving fused attention ops (reference:
incubate/nn/functional/block_multihead_attention.py,
masked_multihead_attention.py, blha_get_max_len.py)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.incubate.nn.functional as IF


def _ref_step_attention(q, kc, vc, lens):
    """Loop reference: per-seq attention over cache[:len+1]."""
    B, H, D = q.shape
    out = np.zeros((B, H, D), np.float32)
    for i in range(B):
        L = int(lens[i]) + 1
        s = np.einsum("hd,hsd->hs", q[i], kc[i, :, :L]) / np.sqrt(D)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        out[i] = np.einsum("hs,hsd->hd", p, vc[i, :, :L])
    return out


def test_blha_get_max_len():
    enc = paddle.to_tensor(np.asarray([5, 2, 9], np.int32))
    dec = paddle.to_tensor(np.asarray([0, 7, 1], np.int32))
    me, md = IF.blha_get_max_len(enc, dec, paddle.to_tensor(np.ones(3)))
    assert int(me.numpy()[0]) == 9 and int(md.numpy()[0]) == 7


def test_masked_multihead_attention_matches_loop():
    rng = np.random.RandomState(0)
    B, H, S, D = 2, 4, 16, 8
    cache = rng.randn(2, B, H, S, D).astype(np.float32)
    lens = np.asarray([3, 7], np.int32)
    x = rng.randn(B, 3 * H * D).astype(np.float32)
    bias = rng.randn(3, H, D).astype(np.float32)

    out, cache2 = IF.masked_multihead_attention(
        paddle.to_tensor(x), cache_kv=paddle.to_tensor(cache),
        bias=paddle.to_tensor(bias),
        sequence_lengths=paddle.to_tensor(lens))
    out, cache2 = np.asarray(out.numpy()), np.asarray(cache2.numpy())

    qkv = x.reshape(B, 3, H, D) + bias.reshape(1, 3, H, D)
    kc, vc = cache[0].copy(), cache[1].copy()
    for i in range(B):
        kc[i, :, lens[i]] = qkv[i, 1]
        vc[i, :, lens[i]] = qkv[i, 2]
    ref = _ref_step_attention(qkv[:, 0], kc, vc, lens)
    np.testing.assert_allclose(out, ref.reshape(B, H * D), atol=2e-5)
    # cache written in place at the right slot, elsewhere untouched
    np.testing.assert_allclose(cache2[0], kc, atol=1e-6)
    np.testing.assert_allclose(cache2[1], vc, atol=1e-6)


def test_masked_mha_long_src_mask_clamped():
    """Regression (ADVICE.md r5): a src_mask whose last dim exceeds the
    cache S_max made the pad width negative (jnp.pad raised). It must
    clamp to S_max — matching the result of passing the pre-clamped
    mask — like the decode tgt_mask path does."""
    rng = np.random.RandomState(7)
    B, H, S, D = 2, 2, 8, 4
    cache = rng.randn(2, B, H, S, D).astype(np.float32)
    lens = np.asarray([3, 6], np.int32)
    x = rng.randn(B, 3 * H * D).astype(np.float32)
    long_mask = rng.randn(B, 1, S + 5).astype(np.float32)  # > S_max

    out_long, _ = IF.masked_multihead_attention(
        paddle.to_tensor(x), cache_kv=paddle.to_tensor(cache),
        src_mask=paddle.to_tensor(long_mask),
        sequence_lengths=paddle.to_tensor(lens))
    out_clamped, _ = IF.masked_multihead_attention(
        paddle.to_tensor(x), cache_kv=paddle.to_tensor(cache),
        src_mask=paddle.to_tensor(long_mask[:, :, :S]),
        sequence_lengths=paddle.to_tensor(lens))
    np.testing.assert_allclose(np.asarray(out_long.numpy()),
                               np.asarray(out_clamped.numpy()),
                               atol=1e-6)
    # short masks still pad up to S_max
    out_short, _ = IF.masked_multihead_attention(
        paddle.to_tensor(x), cache_kv=paddle.to_tensor(cache),
        src_mask=paddle.to_tensor(long_mask[:, :, :2]),
        sequence_lengths=paddle.to_tensor(lens))
    assert np.isfinite(np.asarray(out_short.numpy())).all()


def test_masked_mha_gates_quant_args():
    x = paddle.to_tensor(np.zeros((1, 3 * 2 * 4), np.float32))
    cache = paddle.to_tensor(np.zeros((2, 1, 2, 8, 4), np.float32))
    with pytest.raises(NotImplementedError, match="quantized-cache"):
        IF.masked_multihead_attention(
            x, cache_kv=cache,
            qkv_out_scale=paddle.to_tensor(np.ones(1)))


def _bmha_setup(rng, B, H, D, BS, MB):
    NB = B * MB + 1
    kc = rng.randn(NB, H, BS, D).astype(np.float32)
    vc = rng.randn(NB, H, BS, D).astype(np.float32)
    tables = rng.permutation(NB - 1)[:B * MB].reshape(B, MB) + 1
    return kc, vc, tables.astype(np.int32)


def test_block_mha_decode_matches_loop():
    rng = np.random.RandomState(1)
    B, H, D, BS, MB = 2, 2, 8, 4, 3
    kc, vc, tables = _bmha_setup(rng, B, H, D, BS, MB)
    dec = np.asarray([5, 2], np.int32)     # tokens already cached
    qkv = rng.randn(B, 3 * H * D).astype(np.float32)

    out, _, kc2, vc2 = IF.block_multihead_attention(
        paddle.to_tensor(qkv), paddle.to_tensor(kc),
        paddle.to_tensor(vc),
        paddle.to_tensor(np.zeros(B, np.int32)),       # enc lens
        paddle.to_tensor(dec),
        paddle.to_tensor(np.ones(B, np.int32)),        # this time: 1
        paddle.to_tensor(np.zeros(B, np.int32)),
        paddle.to_tensor(np.zeros(B, np.int32)),
        paddle.to_tensor(np.arange(B + 1, dtype=np.int32)),
        paddle.to_tensor(np.arange(B + 1, dtype=np.int32)),
        paddle.to_tensor(tables), block_size=BS)
    out = np.asarray(out.numpy())

    # loop reference over a dense per-seq cache
    pk = qkv.reshape(B, 3, H, D)
    dense_k = np.zeros((B, H, MB * BS, D), np.float32)
    dense_v = np.zeros((B, H, MB * BS, D), np.float32)
    for i in range(B):
        for m in range(MB):
            dense_k[i, :, m * BS:(m + 1) * BS] = kc[tables[i, m]]
            dense_v[i, :, m * BS:(m + 1) * BS] = vc[tables[i, m]]
        dense_k[i, :, dec[i]] = pk[i, 1]
        dense_v[i, :, dec[i]] = pk[i, 2]
    ref = _ref_step_attention(pk[:, 0], dense_k, dense_v, dec)
    np.testing.assert_allclose(out, ref.reshape(B, H * D), atol=3e-2)
    # the written slot landed in the right page
    kc2 = np.asarray(kc2.numpy())
    pg, sl = tables[0, dec[0] // BS], dec[0] % BS
    np.testing.assert_allclose(kc2[pg, :, sl], pk[0, 1], atol=1e-6)


def test_block_mha_prefill_writes_pages_and_attends_causal():
    rng = np.random.RandomState(2)
    B, H, D, BS, MB = 2, 2, 8, 4, 3
    kc, vc, tables = _bmha_setup(rng, B, H, D, BS, MB)
    lens = np.asarray([6, 3], np.int32)
    T = int(lens.sum())
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    qkv = rng.randn(T, 3 * H * D).astype(np.float32)

    out, _, kc2, vc2 = IF.block_multihead_attention(
        paddle.to_tensor(qkv), paddle.to_tensor(kc),
        paddle.to_tensor(vc),
        paddle.to_tensor(lens),                        # enc lens
        paddle.to_tensor(np.zeros(B, np.int32)),
        paddle.to_tensor(lens),
        paddle.to_tensor(np.zeros(T, np.int32)),
        paddle.to_tensor(np.zeros(B, np.int32)),
        paddle.to_tensor(cu), paddle.to_tensor(cu),
        paddle.to_tensor(tables), block_size=BS)
    out = np.asarray(out.numpy())

    pk = qkv.reshape(T, 3, H, D)
    for i in range(B):
        q = pk[cu[i]:cu[i + 1], 0]
        k = pk[cu[i]:cu[i + 1], 1]
        v = pk[cu[i]:cu[i + 1], 2]
        L = int(lens[i])
        s = np.einsum("thd,shd->hts", q, k) / np.sqrt(D)
        mask = np.tril(np.ones((L, L), bool))
        s = np.where(mask[None], s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("hts,shd->thd", p, v).reshape(L, H * D)
        np.testing.assert_allclose(out[cu[i]:cu[i + 1]], ref, atol=2e-5)
    # cached prompt K readable back through the tables
    kc2 = np.asarray(kc2.numpy())
    tok = 5                                            # seq 0, pos 5
    pg, sl = tables[0, tok // BS], tok % BS
    np.testing.assert_allclose(kc2[pg, :, sl], pk[tok, 1], atol=1e-6)


def test_block_mha_decode_honors_tgt_mask():
    """An additive tgt_mask that blanks all but position 0 must change
    the output to attend only there (regression: the mask used to be
    silently ignored)."""
    rng = np.random.RandomState(4)
    B, H, D, BS, MB = 1, 2, 8, 4, 2
    kc, vc, tables = _bmha_setup(rng, B, H, D, BS, MB)
    dec = np.asarray([3], np.int32)
    qkv = rng.randn(B, 3 * H * D).astype(np.float32)
    S = MB * BS
    neg = np.full((B, 1, 1, S), -1e9, np.float32)
    neg[:, :, :, 0] = 0.0

    def run(mask):
        out = IF.block_multihead_attention(
            paddle.to_tensor(qkv), paddle.to_tensor(kc),
            paddle.to_tensor(vc),
            paddle.to_tensor(np.zeros(B, np.int32)),
            paddle.to_tensor(dec),
            paddle.to_tensor(np.ones(B, np.int32)),
            paddle.to_tensor(np.zeros(B, np.int32)),
            paddle.to_tensor(np.zeros(B, np.int32)),
            paddle.to_tensor(np.arange(B + 1, dtype=np.int32)),
            paddle.to_tensor(np.arange(B + 1, dtype=np.int32)),
            paddle.to_tensor(tables), block_size=BS,
            tgt_mask=mask)[0]
        return np.asarray(out.numpy())

    masked = run(paddle.to_tensor(neg))
    # attending only to position 0 == that position's value rows
    v0 = vc[tables[0, 0], :, 0]                    # [H, D]
    np.testing.assert_allclose(masked.reshape(H, D), v0, atol=1e-4)
    unmasked = run(None)
    assert np.abs(masked - unmasked).max() > 1e-3


def test_block_mha_rejects_mixed_phase():
    rng = np.random.RandomState(3)
    B, H, D, BS, MB = 2, 2, 8, 4, 2
    kc, vc, tables = _bmha_setup(rng, B, H, D, BS, MB)
    with pytest.raises(NotImplementedError, match="mixed"):
        IF.block_multihead_attention(
            paddle.to_tensor(rng.randn(2, 3 * H * D).astype(np.float32)),
            paddle.to_tensor(kc), paddle.to_tensor(vc),
            paddle.to_tensor(np.asarray([4, 0], np.int32)),  # enc
            paddle.to_tensor(np.asarray([0, 2], np.int32)),  # dec
            paddle.to_tensor(np.ones(B, np.int32)),
            paddle.to_tensor(np.zeros(B, np.int32)),
            paddle.to_tensor(np.zeros(B, np.int32)),
            paddle.to_tensor(np.arange(B + 1, dtype=np.int32)),
            paddle.to_tensor(np.arange(B + 1, dtype=np.int32)),
            paddle.to_tensor(tables), block_size=BS)


def test_block_mha_decode_int8_static_cache():
    """Static int8 cache mode (reference block_attn.h int8 path): the
    decode step over quantized pools tracks the bf16 result within
    quantization tolerance, and the written slot is int8."""
    from paddle_tpu.ops.paged_attention import quantize_pools
    rng = np.random.RandomState(5)
    B, H, D, BS, MB = 2, 2, 8, 4, 3
    kc, vc, tables = _bmha_setup(rng, B, H, D, BS, MB)
    dec = np.asarray([5, 2], np.int32)
    qkv = rng.randn(B, 3 * H * D).astype(np.float32)
    common = [
        paddle.to_tensor(np.zeros(B, np.int32)), paddle.to_tensor(dec),
        paddle.to_tensor(np.ones(B, np.int32)),
        paddle.to_tensor(np.zeros(B, np.int32)),
        paddle.to_tensor(np.zeros(B, np.int32)),
        paddle.to_tensor(np.arange(B + 1, dtype=np.int32)),
        paddle.to_tensor(np.arange(B + 1, dtype=np.int32)),
        paddle.to_tensor(tables)]

    ref_out = IF.block_multihead_attention(
        paddle.to_tensor(qkv), paddle.to_tensor(kc),
        paddle.to_tensor(vc), *common, block_size=BS)[0].numpy()

    # quantize [NB, H, BS, D] -> pool layout and back
    kq, vq, ks, vs = quantize_pools(jnp.swapaxes(jnp.asarray(kc), 1, 2),
                                    jnp.swapaxes(jnp.asarray(vc), 1, 2))
    kq8 = np.asarray(jnp.swapaxes(kq, 1, 2))
    vq8 = np.asarray(jnp.swapaxes(vq, 1, 2))
    out, _, kc2, _ = IF.block_multihead_attention(
        paddle.to_tensor(qkv), paddle.to_tensor(kq8),
        paddle.to_tensor(vq8), *common, block_size=BS,
        cache_k_dequant_scales=paddle.to_tensor(np.asarray(ks)),
        cache_v_dequant_scales=paddle.to_tensor(np.asarray(vs)))
    rel = np.abs(out.numpy() - ref_out).max() / (
        np.abs(ref_out).max() + 1e-9)
    assert rel < 0.05, rel
    assert np.asarray(kc2.numpy()).dtype == np.int8


def test_generate_paged_int8_cache_close_logits_and_runs():
    """generate_paged(cache_dtype='int8'): the per-step decode logits
    over quantized pools track the bf16-cache logits within quant
    tolerance (token chains on a RANDOM model legally diverge at
    near-ties, so logits — not greedy chains — are the right check),
    and the end-to-end int8 loop runs with int8 pools."""
    from paddle_tpu.inference import generation as G
    from paddle_tpu.models.llama import LlamaConfig, init_params
    from paddle_tpu.ops.paged_attention import quantize_pools

    cfg = LlamaConfig(vocab_size=256, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=4,
                      max_position_embeddings=96, dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    B, S, BS, MB = 2, 16, 8, 4
    k_cache, v_cache = G.init_cache(cfg, B, MB * BS)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 256, (B, S)),
                       jnp.int32)
    logits, k_cache, v_cache = G.cached_forward(
        params, toks, cfg, k_cache, v_cache, 0)
    # repack densely into per-seq pages (identity tables)
    L, KV, hd = cfg.num_hidden_layers, 4, cfg.head_dim
    NB = B * MB
    kp = jnp.reshape(k_cache, (L, NB, BS, KV, hd))
    vp = jnp.reshape(v_cache, (L, NB, BS, KV, hd))
    tables = jnp.asarray(
        np.arange(NB).reshape(B, MB), jnp.int32)
    lens = jnp.full((B,), S, jnp.int32)
    tok = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)

    lg_bf, _, _ = G._paged_decode_step(params, tok, cfg, kp, vp,
                                       tables, lens)
    kq, vq, ks, vs = jax.vmap(quantize_pools)(kp, vp)
    lg_i8, kq2, _ = G._paged_decode_step(params, tok, cfg, kq, vq,
                                         tables, lens,
                                         kv_scales=(ks, vs))
    assert kq2.dtype == jnp.int8
    rel = float(jnp.max(jnp.abs(lg_i8 - lg_bf))
                / (jnp.max(jnp.abs(lg_bf)) + 1e-9))
    assert rel < 0.05, rel

    # end-to-end int8 serving loop runs and emits valid tokens
    g = G.GenerationConfig(max_new_tokens=8, greedy=True)
    out = np.asarray(G.generate_paged(params, toks, cfg, g,
                                      cache_dtype="int8"))
    assert out.shape == (B, S + 8)
    assert ((0 <= out) & (out < 256)).all()
