"""Continuous-batching ServingEngine (inference/serving.py): exact
parity with single-request generate, slot recycle + page release, and
the zero-retrace steady state (<=1 trace per prefill bucket + 1 decode
program over a 30-request mixed-arrival stream)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.models import llama
from paddle_tpu.inference import (GenerationConfig, ServingEngine,
                                  generate)

CFG = llama.LlamaConfig(vocab_size=97, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        max_position_embeddings=128, dtype=jnp.float32,
                        remat=False)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _engine(params, **kw):
    kw.setdefault("capacity", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("max_seq_len", 64)
    return ServingEngine(params, CFG, **kw)


def test_outputs_match_single_request_generate(params):
    """Per-request greedy outputs must equal generate() exactly, across
    mixed prompt lengths / max_new_tokens and with capacity < requests
    (so admission waits and slots recycle mid-stream)."""
    rng = np.random.RandomState(0)
    eng = _engine(params)
    specs = [(5, 6), (9, 4), (13, 5), (7, 3), (21, 5)]  # (S, N); 21 > 16
    reqs = []                                           # -> multi-chunk
    for S, N in specs:
        p = rng.randint(0, 97, (S,)).astype(np.int32)
        reqs.append((p, eng.submit(
            p, GenerationConfig(max_new_tokens=N, greedy=True))))
    eng.drain()
    for (S, N), (p, r) in zip(specs, reqs):
        want = np.asarray(generate(
            params, jnp.asarray(p)[None], CFG,
            GenerationConfig(max_new_tokens=N, greedy=True)))[0, S:]
        np.testing.assert_array_equal(np.asarray(r.tokens), want)
        assert r.done and r.ttft is not None


def test_slot_recycle_and_page_release(params):
    """Finished requests must release every KV page and free their slot
    for the queue; a stream of 6 requests through 2 slots only works if
    recycling does."""
    rng = np.random.RandomState(1)
    eng = _engine(params, capacity=2)
    free0 = len(eng.mgr.free)
    rs = [eng.submit(rng.randint(0, 97, (6,)).astype(np.int32),
                     GenerationConfig(max_new_tokens=4, greedy=True))
          for _ in range(6)]
    # mid-stream: at most 2 in flight, the rest queued on slots
    eng.step()
    in_flight = sum(s.phase != "idle" for s in eng._slots)
    assert 1 <= in_flight <= 2
    assert len(eng.mgr.free) < free0
    eng.drain()
    assert all(r.done for r in rs)
    assert eng.counters["requests_completed"] == 6
    assert len(eng.mgr.free) == free0        # every page came back
    assert all(s.phase == "idle" for s in eng._slots)
    assert eng.idle


def test_steady_state_traces_over_30_request_stream(params):
    """The acceptance bar: a 30-request mixed-arrival stream (staggered
    submits, mixed lengths, greedy and sampled) completes with exactly
    1 decode program and <=1 trace per prefill bucket."""
    rng = np.random.RandomState(2)
    eng = _engine(params, capacity=3)
    pending = []
    for i in range(30):
        S = int(rng.randint(3, 17))
        N = int(rng.randint(2, 7))
        g = GenerationConfig(max_new_tokens=N, greedy=bool(i % 2),
                             temperature=0.8)
        pending.append((rng.randint(0, 97, (S,)).astype(np.int32), g))
    submitted = []
    # mixed arrivals: a few requests trickle in between scheduler steps
    while pending or not eng.idle:
        for _ in range(min(len(pending), 1 + int(rng.randint(0, 3)))):
            p, g = pending.pop(0)
            submitted.append(eng.submit(p, g))
        eng.step()
    assert len(submitted) == 30
    assert all(r.done for r in submitted)
    c = eng.counters
    assert c["requests_completed"] == 30
    assert c["decode_traces"] == 1, c
    assert set(c["prefill_traces"]) <= {8, 16}
    assert all(n <= 1 for n in c["prefill_traces"].values()), c
    assert c["calibration_traces"] == 0
    m = eng.metrics()
    assert 0.0 < m["slot_utilization"] <= 1.0
    assert m["tokens_per_sec"] > 0
    assert m["ttft_ms_mean"] is not None and m["ttft_ms_mean"] > 0


def test_eos_stops_request_early(params):
    rng = np.random.RandomState(3)
    eng = _engine(params)
    p = rng.randint(0, 97, (9,)).astype(np.int32)
    g = GenerationConfig(max_new_tokens=6, greedy=True)
    probe = eng.submit(p, g)
    eng.drain()
    eos = probe.tokens[1]           # force eos at a greedy token
    expect = probe.tokens[:probe.tokens.index(eos) + 1]
    r = eng.submit(p, GenerationConfig(max_new_tokens=6, greedy=True,
                                       eos_token_id=eos))
    eng.drain()
    assert r.tokens == expect       # stops AT the first eos occurrence
    assert r.done and len(r.tokens) < 6


def test_int8_cache_path(params):
    """cache_dtype='int8': pools store int8, scales calibrate once from
    the first admitted prompt, and the greedy stream completes with
    valid tokens (token-exactness vs fp is not guaranteed under
    quantization; logits tolerance is covered in
    test_serving_attention)."""
    rng = np.random.RandomState(4)
    eng = _engine(params, cache_dtype="int8")
    rs = [eng.submit(rng.randint(0, 97, (s,)).astype(np.int32),
                     GenerationConfig(max_new_tokens=5, greedy=True))
          for s in (6, 11, 9)]
    eng.drain()
    assert eng._k_pools.dtype == jnp.int8
    assert eng.counters["calibration_traces"] == 1
    assert eng.counters["decode_traces"] == 1
    for r in rs:
        assert len(r.tokens) == 5
        assert all(0 <= t < 97 for t in r.tokens)


def test_submit_validation(params):
    eng = _engine(params)
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.submit(np.zeros(60, np.int32),
                   GenerationConfig(max_new_tokens=10))
    with pytest.raises(ValueError, match="empty"):
        eng.submit(np.zeros(0, np.int32))
    with pytest.raises(NotImplementedError, match="top-k"):
        eng.submit(np.zeros(4, np.int32),
                   GenerationConfig(max_new_tokens=2, top_k=5))


def test_backpressure_waits_for_pages(params):
    """A request that fits the pool but not the CURRENT free pages must
    wait in the queue (FIFO) and run after a release — not crash the
    allocator."""
    rng = np.random.RandomState(5)
    # pool of 9 usable pages (block_size 4): two 24-token requests use
    # 6 pages each, so the second waits for the first to finish
    eng = _engine(params, capacity=2, num_blocks=10)
    g = GenerationConfig(max_new_tokens=4, greedy=True)
    r1 = eng.submit(rng.randint(0, 97, (20,)).astype(np.int32), g)
    r2 = eng.submit(rng.randint(0, 97, (20,)).astype(np.int32), g)
    eng.step()
    assert sum(s.phase != "idle" for s in eng._slots) == 1  # r2 queued
    eng.drain()
    assert r1.done and r2.done
    assert len(r1.tokens) == 4 and len(r2.tokens) == 4
