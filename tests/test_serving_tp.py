"""Tensor-parallel sharded serving (inference/tp.py +
ServingEngine(mesh=...) + generate_paged(mesh=...)) on the forced
8-device virtual CPU mesh (conftest).

The acceptance bar (ISSUE 9): a tp-sharded engine serves a 20+-request
mixed-arrival stream with greedy parity vs the single-device engine —
BIT-identical for the documented collective="gather" placement,
token-identical for the default "psum" placement — with exactly 1
decode program and <=1 trace per prefill bucket under tp=2 and tp=4,
prefix-cache warm-vs-cold parity under sharding, clean rejection of
non-divisible head counts, and the sharded decode jaxpr carrying
exactly its DECLARED collectives (the jax_compat.axis_size static-
lookup regression)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.models import llama
from paddle_tpu.inference import (GenerationConfig, ServingEngine,
                                  ServingMesh, generate_paged)
from paddle_tpu.inference.tp import tp_reject_reason

pytestmark = pytest.mark.serving_tp

CFG = llama.LlamaConfig(vocab_size=97, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=4,
                        max_position_embeddings=160,
                        dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


@pytest.fixture(scope="module")
def ref_stream(params):
    """The single-device engine's greedy output over THE 22-request
    mixed-arrival stream — the parity reference every placement is
    held to (computed once per module)."""
    return _mixed_stream(_engine(params))


def _engine(params, mesh=None, **kw):
    kw.setdefault("capacity", 3)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("max_seq_len", 64)
    return ServingEngine(params, CFG, mesh=mesh, **kw)


def _mixed_stream(eng, n=22, seed=7, max_new=5):
    """n requests arriving in WAVES interleaved with engine steps, so
    admission happens while other slots are mid-prefill/decode (the
    continuous-batching path, not one static batch)."""
    rng = np.random.RandomState(seed)
    sizes = rng.randint(4, 14, n)
    reqs = []
    for i, s in enumerate(sizes):
        reqs.append(eng.submit(
            rng.randint(0, 97, (int(s),)).astype(np.int32),
            GenerationConfig(max_new_tokens=max_new, greedy=True)))
        if i % 3 == 2:           # a couple of steps between waves
            eng.step()
            eng.step()
    eng.drain()
    return [r.output_ids for r in reqs]


def _same(a, b):
    return all(np.array_equal(x, y) for x, y in zip(a, b))


# -- greedy parity over a 20+-request mixed-arrival stream -------------

def test_gather_bit_parity_tp2_tp4_and_program_counts(params,
                                                      ref_stream):
    """collective="gather" is the documented BIT-identical placement:
    every matmul sees the exact single-device operands. One decode
    program + <=1 trace per prefill bucket must hold under sharding."""
    ref = ref_stream
    for tp in (2, 4):
        eng = _engine(params,
                      mesh=ServingMesh.make(tp=tp, collective="gather"))
        out = _mixed_stream(eng)
        assert _same(ref, out), f"tp={tp} greedy output diverged"
        m = eng.metrics()
        assert m["decode_traces"] == 1
        assert all(v <= 1 for v in m["prefill_traces"].values())
        assert m["mesh"] == {"axis": "tp", "tp": tp,
                             "collective": "gather"}


def test_psum_token_parity_tp4(params, ref_stream):
    """The default "psum" placement re-associates the o/down-proj
    reductions (documented roundoff-parity); greedy TOKENS must still
    agree on this fixed stream."""
    eng = _engine(params, mesh=ServingMesh.make(tp=4,
                                                collective="psum"))
    out = _mixed_stream(eng)
    assert _same(ref_stream, out)
    assert eng.metrics()["decode_traces"] == 1


def test_tp1_mesh_is_bit_identical_both_placements(params):
    """A 1-shard mesh is the identity: both placements must match the
    meshless engine bit-for-bit (psum/all_gather over one device)."""
    ref = _mixed_stream(_engine(params), n=6)
    for coll in ("psum", "gather"):
        out = _mixed_stream(
            _engine(params, mesh=ServingMesh.make(tp=1,
                                                  collective=coll)),
            n=6)
        assert _same(ref, out), coll


def test_zero_steady_state_retraces_after_warmup(params):
    eng = _engine(params, mesh=ServingMesh.make(tp=2),
                  observability=True)
    _mixed_stream(eng, n=8)
    eng.reset_metrics()          # arms the retrace watchdog
    _mixed_stream(eng, n=8, seed=11)
    m = eng.metrics()
    assert m["retrace_warnings"] == 0
    assert m["decode_traces"] == 1


# -- prefix cache under sharding ---------------------------------------

def test_prefix_cache_warm_vs_cold_parity_under_sharding(params):
    """The radix tree shares page INDICES; pages shard their head-dim
    contents — COW/eviction logic is untouched, and a warm sharded
    request must produce bit-identical output to the cold one."""
    mesh = ServingMesh.make(tp=2, collective="gather")
    ref = _mixed_stream(_engine(params), n=8)
    eng = _engine(params, mesh=mesh, prefix_cache=True)
    cold = _mixed_stream(eng, n=8)
    assert _same(ref, cold)
    warm = _mixed_stream(eng, n=8)      # same seed -> same prompts
    assert _same(cold, warm)
    assert eng.metrics()["prefix_cache"]["hits"] > 0


def test_int8_cache_sharded_parity(params):
    """int8 pools shard like bf16 ones (scales shard with their KV
    heads); sharded int8 greedy output must match single-device int8
    bit-for-bit under the gather placement."""
    ref = _mixed_stream(_engine(params, cache_dtype="int8"), n=8)
    out = _mixed_stream(
        _engine(params, cache_dtype="int8",
                mesh=ServingMesh.make(tp=2, collective="gather")), n=8)
    assert _same(ref, out)


# -- rejection / construction ------------------------------------------

def test_non_divisible_head_count_rejected_with_reason(params):
    ok, reason = ServingMesh.make(tp=3).supports(CFG)
    assert not ok and "not divisible by tp=3" in reason
    with pytest.raises(ValueError, match="not divisible by tp=3"):
        _engine(params, mesh=ServingMesh.make(tp=3))
    assert tp_reject_reason(CFG, 4) is None
    assert "intermediate_size" in tp_reject_reason(
        llama.LlamaConfig(vocab_size=97, hidden_size=64,
                          intermediate_size=101, num_hidden_layers=1,
                          num_attention_heads=4,
                          num_key_value_heads=4), 2)


def test_mesh_argument_normalization(params):
    from jax.sharding import Mesh
    eng = _engine(params, mesh=2)                   # int tp degree
    assert eng.metrics()["mesh"]["tp"] == 2
    raw = Mesh(np.array(jax.devices()[:2]), ("model",))
    eng = _engine(params, mesh=raw)                 # bare 1-D jax mesh
    assert eng.metrics()["mesh"]["axis"] == "model"
    with pytest.raises(ValueError, match="1-D mesh"):
        _engine(params, mesh=Mesh(
            np.array(jax.devices()[:4]).reshape(2, 2), ("a", "b")))
    with pytest.raises(ValueError, match="collective"):
        ServingMesh.make(tp=2, collective="allgatherz")
    # an explicit pallas pin must RAISE under the gather placement
    # (which runs the exact composition by contract), never no-op
    with pytest.raises(ValueError, match="gather"):
        _engine(params, fused_decode="pallas",
                mesh=ServingMesh.make(tp=2, collective="gather"))


# -- collective observability ------------------------------------------

def test_flight_recorder_counts_declared_collectives(params):
    eng = _engine(params, mesh=ServingMesh.make(tp=2,
                                                collective="psum"),
                  observability=True)
    _mixed_stream(eng, n=6)
    m = eng.metrics()
    col = m["collectives"]
    # psum placement: one aggregated task per decode step / prefill
    # chunk, byte counts from the static [2L, B, D] payload shape
    assert col["calls"]["psum@tp"] > 0
    assert col["bytes"]["psum@tp"] > 0
    snap = col["latency_ms"]["psum@tp"]
    assert snap["count"] == col["calls"]["psum@tp"]
    # raw recorder counters never leak as top-level metric keys
    assert "collective_calls" not in m and "collective_bytes" not in m
    # reset_metrics restarts call/byte counters WITH the latency
    # histograms: the collectives sub-dict always reports one window
    eng.reset_metrics()
    _mixed_stream(eng, n=3, seed=5)
    m = eng.metrics()
    col = m["collectives"]
    assert col["calls"]["psum@tp"] == \
        col["latency_ms"]["psum@tp"]["count"] > 0
    # gather placement names its op accordingly
    eng2 = _engine(params, mesh=ServingMesh.make(tp=2,
                                                 collective="gather"),
                   observability=True)
    _mixed_stream(eng2, n=4)
    assert eng2.metrics()["collectives"]["calls"]["all_gather@tp"] > 0


# -- generate_paged(mesh=...) ------------------------------------------

def test_generate_paged_mesh_parity(params):
    rng = np.random.RandomState(3)
    ids = jnp.asarray(rng.randint(0, 97, (3, 12)).astype(np.int32))
    g = GenerationConfig(max_new_tokens=8, greedy=True)
    ref = np.asarray(generate_paged(params, ids, CFG, g))
    got = np.asarray(generate_paged(
        params, ids, CFG, g,
        mesh=ServingMesh.make(tp=4, collective="gather")))
    assert np.array_equal(ref, got)
    tok = np.asarray(generate_paged(
        params, ids, CFG, g,
        mesh=ServingMesh.make(tp=2, collective="psum")))
    assert np.array_equal(ref, tok)


def test_generate_paged_mesh_rejects_prefix_store(params):
    from paddle_tpu.inference import PagedKVCacheStore
    store = PagedKVCacheStore(CFG, num_blocks=32, block_size=4)
    with pytest.raises(NotImplementedError, match="ServingEngine"):
        generate_paged(params, jnp.zeros((1, 4), jnp.int32), CFG,
                       GenerationConfig(max_new_tokens=2, greedy=True),
                       block_size=4, prefix_cache=store, mesh=2)


# -- declared-collectives jaxpr regression (axis_size satellite) -------

def _collective_counts(jaxpr, counts):
    from paddle_tpu.analysis.rules import iter_subjaxprs
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in ("psum", "all_gather", "ppermute",
                                  "all_to_all", "reduce_scatter"):
            counts[eqn.primitive.name] = \
                counts.get(eqn.primitive.name, 0) + 1
        for _, sub, _ in iter_subjaxprs(eqn):
            _collective_counts(sub, counts)
    return counts


@pytest.mark.parametrize("coll,expect", [
    ("psum", {"psum": 2}),           # one per sub-block, in the scan body
    ("gather", {"all_gather": 2}),
])
def test_decode_jaxpr_carries_exactly_declared_collectives(
        params, coll, expect):
    """jax_compat.axis_size resolves STATICALLY: the sharded decode
    jaxpr must contain exactly the two declared collectives per layer
    scan body and nothing else — a psum(1, axis) fallback emitting a
    collective per axis_size call site would show up here."""
    eng = _engine(params, mesh=ServingMesh.make(tp=2, collective=coll))
    spec = [s for s in eng.program_specs(register=False)
            if s.name == "serving_decode_tp"][0]
    closed = jax.make_jaxpr(spec.fn)(*spec.args)
    counts = _collective_counts(closed.jaxpr, {})
    assert counts == expect, counts


def test_axis_size_static_lookup_inside_shard_map():
    from paddle_tpu.core.jax_compat import axis_size, shard_map_norep
    from jax.sharding import Mesh, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()[:4]), ("tp",))

    def body(x):
        return x * axis_size("tp")

    out = jax.jit(shard_map_norep(body, mesh, P("tp"), P("tp")))(
        jnp.ones((4, 2)))
    assert float(np.asarray(out)[0, 0]) == 4.0
    closed = jax.make_jaxpr(jax.jit(shard_map_norep(
        body, mesh, P("tp"), P("tp"))))(jnp.ones((4, 2)))
    assert _collective_counts(closed.jaxpr, {}) == {}


# -- audit wiring ------------------------------------------------------

def test_catalog_tp_specs_audit_clean():
    from paddle_tpu.analysis import audit_spec
    from paddle_tpu.analysis.catalog import (CATALOG_PROGRAMS,
                                             build_catalog)
    assert "serving_decode_tp" in CATALOG_PROGRAMS
    assert "serving_prefill_tp_16" in CATALOG_PROGRAMS
    specs = build_catalog(names=["serving_decode_tp",
                                 "serving_prefill_tp_16"],
                          register=False)
    assert sorted(s.name for s in specs) == [
        "serving_decode_tp", "serving_prefill_tp_16"]
    for s in specs:
        assert s.mesh_axes == ("tp",)
        rep = audit_spec(s)
        assert rep.findings == [], [f.fingerprint for f in rep.findings]


def test_demo_tp_regression_fires_unknown_axis():
    """The mismatched-axis injection: the REAL per-shard decode body
    declared over the wrong mesh axis must trip the collective rule."""
    from paddle_tpu.analysis import audit_spec
    from paddle_tpu.analysis.catalog import build_demo_tp_regression
    rep = audit_spec(build_demo_tp_regression())
    codes = {f.code for f in rep.findings}
    assert "UNKNOWN_COLLECTIVE_AXIS" in codes, codes
    f = next(f for f in rep.findings
             if f.code == "UNKNOWN_COLLECTIVE_AXIS")
    assert f.detail["axis"] == "tp"
    assert f.detail["in_scope"] == ["model"]


def test_fused_meta_grows_tp_field_and_key_declares_it():
    from paddle_tpu.ops.pallas.fused_decode_block import (
        _DECODE_KEY_FIELDS, decode_meta_dims)
    from paddle_tpu.ops.pallas.registry import KERNELS
    meta = decode_meta_dims(2, 64, 2, 2, 16, 64, 8, 8, jnp.float32,
                            jnp.float32, False, tp=2)
    assert meta["tp"] == 2
    assert "tp" in _DECODE_KEY_FIELDS
    fields, _covers = KERNELS.cache_key_decl("decode_attn_block")
    assert "tp" in fields
