"""Sparse tensor + geometric op tests (reference: test/legacy_test
sparse_* tests + test/geometric suites)."""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import sparse, geometric


# -- sparse COO/CSR ---------------------------------------------------------
def test_coo_roundtrip():
    idx = np.array([[0, 1, 2], [1, 0, 2]])
    vals = np.array([1.0, 2.0, 3.0], np.float32)
    sp = sparse.sparse_coo_tensor(idx, vals, (3, 3))
    assert sp.nnz == 3
    dense = sp.to_dense().numpy()
    expect = np.zeros((3, 3), np.float32)
    expect[0, 1], expect[1, 0], expect[2, 2] = 1, 2, 3
    np.testing.assert_allclose(dense, expect)


def test_coo_coalesce_sums_duplicates():
    idx = np.array([[0, 0, 1], [1, 1, 0]])
    vals = np.array([1.0, 4.0, 2.0], np.float32)
    sp = sparse.sparse_coo_tensor(idx, vals, (2, 2)).coalesce()
    assert sp.nnz == 2
    assert sp.to_dense().numpy()[0, 1] == pytest.approx(5.0)


def test_csr_roundtrip_and_conversion():
    dense = np.array([[1, 0, 2], [0, 0, 3], [4, 0, 0]], np.float32)
    coo = sparse.to_sparse_coo(paddle.to_tensor(dense))
    csr = coo.to_sparse_csr()
    np.testing.assert_array_equal(np.asarray(csr.crows().numpy()),
                                  [0, 2, 3, 4])
    np.testing.assert_allclose(csr.to_dense().numpy(), dense)
    back = csr.to_sparse_coo()
    np.testing.assert_allclose(back.to_dense().numpy(), dense)


def test_sparse_elementwise_and_relu():
    d1 = np.array([[1, -2], [0, 3]], np.float32)
    d2 = np.array([[5, 1], [0, -1]], np.float32)
    s1 = sparse.to_sparse_coo(paddle.to_tensor(d1))
    s2 = sparse.to_sparse_coo(paddle.to_tensor(d2))
    np.testing.assert_allclose(sparse.add(s1, s2).to_dense().numpy(),
                               d1 + d2)
    r = sparse.relu(s1).to_dense().numpy()
    np.testing.assert_allclose(r, np.maximum(d1, 0))


def test_spmm_matches_dense():
    rng = np.random.RandomState(0)
    dense = rng.randn(6, 5).astype(np.float32)
    dense[rng.rand(6, 5) > 0.4] = 0.0
    y = rng.randn(5, 4).astype(np.float32)
    sp = sparse.to_sparse_coo(paddle.to_tensor(dense))
    out = sparse.matmul(sp, paddle.to_tensor(y)).numpy()
    np.testing.assert_allclose(out, dense @ y, rtol=1e-5, atol=1e-5)
    # CSR path
    out2 = sparse.matmul(sp.to_sparse_csr(), paddle.to_tensor(y)).numpy()
    np.testing.assert_allclose(out2, dense @ y, rtol=1e-5, atol=1e-5)


def test_masked_matmul_sddmm():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 3).astype(np.float32)
    y = rng.randn(3, 4).astype(np.float32)
    mask_d = np.zeros((4, 4), np.float32)
    mask_d[0, 1] = mask_d[2, 3] = 1
    mask = sparse.to_sparse_coo(paddle.to_tensor(mask_d))
    out = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y),
                               mask)
    full = x @ y
    got = out.to_dense().numpy()
    assert got[0, 1] == pytest.approx(full[0, 1], rel=1e-5)
    assert got[2, 3] == pytest.approx(full[2, 3], rel=1e-5)
    assert got[1, 1] == 0


def test_sparse_transpose():
    d = np.array([[1, 0], [2, 3]], np.float32)
    sp = sparse.to_sparse_coo(paddle.to_tensor(d))
    np.testing.assert_allclose(
        sparse.transpose(sp, [1, 0]).to_dense().numpy(), d.T)


# -- geometric --------------------------------------------------------------
def test_segment_ops():
    data = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]],
                                     np.float32))
    ids = np.array([0, 0, 1])
    np.testing.assert_allclose(
        geometric.segment_sum(data, ids, 2).numpy(),
        [[4, 6], [5, 6]])
    np.testing.assert_allclose(
        geometric.segment_mean(data, ids, 2).numpy(),
        [[2, 3], [5, 6]])
    np.testing.assert_allclose(
        geometric.segment_max(data, ids, 2).numpy(),
        [[3, 4], [5, 6]])
    np.testing.assert_allclose(
        geometric.segment_min(data, ids, 2).numpy(),
        [[1, 2], [5, 6]])


def test_send_u_recv_sum_and_mean():
    x = paddle.to_tensor(np.array([[1.], [2.], [3.]], np.float32))
    src = np.array([0, 1, 2, 0])
    dst = np.array([1, 2, 0, 2])
    out = geometric.send_u_recv(x, src, dst, "sum").numpy()
    np.testing.assert_allclose(out, [[3], [1], [3]])
    out_mean = geometric.send_u_recv(x, src, dst, "mean").numpy()
    np.testing.assert_allclose(out_mean, [[3], [1], [1.5]])


def test_send_u_recv_max_empty_segment_zero():
    x = paddle.to_tensor(np.array([[1.], [5.]], np.float32))
    src = np.array([0])
    dst = np.array([0])
    out = geometric.send_u_recv(x, src, dst, "max", out_size=2).numpy()
    np.testing.assert_allclose(out, [[1], [0]])   # node 1: no in-edges → 0


def test_send_ue_recv():
    x = paddle.to_tensor(np.array([[1.], [2.]], np.float32))
    e = paddle.to_tensor(np.array([[10.], [20.]], np.float32))
    src = np.array([0, 1])
    dst = np.array([1, 0])
    out = geometric.send_ue_recv(x, e, src, dst, "add", "sum").numpy()
    np.testing.assert_allclose(out, [[22], [11]])
    out2 = geometric.send_ue_recv(x, e, src, dst, "mul", "sum").numpy()
    np.testing.assert_allclose(out2, [[40], [10]])


def test_sample_neighbors():
    # CSC: node0 ← {1,2}, node1 ← {0}, node2 ← {0,1}
    row = np.array([1, 2, 0, 0, 1])
    colptr = np.array([0, 2, 3, 5])
    neigh, counts = geometric.sample_neighbors(row, colptr,
                                               np.array([0, 2]))
    assert list(counts.numpy()) == [2, 2]
    assert set(np.asarray(neigh.numpy())[:2]) == {1, 2}
    neigh2, counts2 = geometric.sample_neighbors(
        row, colptr, np.array([0]), sample_size=1)
    assert list(counts2.numpy()) == [1]
    assert int(neigh2.numpy()[0]) in (1, 2)


def test_message_passing_gradients_flow():
    """Regression: geometric/sparse ops must record GradNodes so upstream
    layers train."""
    import paddle_tpu.nn as nn
    lin = nn.Linear(3, 3)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 3)
                         .astype(np.float32))
    src = np.array([0, 1, 2, 3])
    dst = np.array([1, 2, 3, 0])
    h = lin(x)
    agg = geometric.send_u_recv(h, src, dst, "sum")
    agg.sum().backward()
    assert lin.weight.grad is not None
    assert float(np.abs(np.asarray(lin.weight.grad.numpy())).sum()) > 0


def test_sparse_matmul_gradient_to_dense_operand():
    dense = np.array([[1., 0.], [0., 2.]], np.float32)
    sp = sparse.to_sparse_coo(paddle.to_tensor(dense))
    y = paddle.to_tensor(np.ones((2, 3), np.float32))
    y.stop_gradient = False
    out = sparse.matmul(sp, y)
    out.sum().backward()
    # d(sum)/dy = sp^T @ ones = column sums of sp rows
    np.testing.assert_allclose(y.grad.numpy(),
                               dense.T @ np.ones((2, 3), np.float32))


def test_to_sparse_coo_partial_dim_no_duplicates():
    v = np.array([[1., 2.]], np.float32)   # one row, trailing dim dense
    sp = sparse.to_sparse_coo(paddle.to_tensor(v), sparse_dim=1)
    assert sp.nnz == 1
    np.testing.assert_allclose(np.asarray(sp.values().numpy()), [[1., 2.]])


def test_gcn_layer_end_to_end():
    """Mini GCN aggregation: normalize-by-degree message passing."""
    n = 4
    edges = np.array([[0, 1], [1, 2], [2, 3], [3, 0], [1, 0]])
    src, dst = edges[:, 0], edges[:, 1]
    x = paddle.to_tensor(np.eye(n, dtype=np.float32))
    agg = geometric.send_u_recv(x, src, dst, "mean", out_size=n)
    assert agg.numpy().shape == (n, n)
    assert np.isfinite(agg.numpy()).all()
