"""Sparse tensor + geometric op tests (reference: test/legacy_test
sparse_* tests + test/geometric suites)."""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import sparse, geometric


# -- sparse COO/CSR ---------------------------------------------------------
def test_coo_roundtrip():
    idx = np.array([[0, 1, 2], [1, 0, 2]])
    vals = np.array([1.0, 2.0, 3.0], np.float32)
    sp = sparse.sparse_coo_tensor(idx, vals, (3, 3))
    assert sp.nnz == 3
    dense = sp.to_dense().numpy()
    expect = np.zeros((3, 3), np.float32)
    expect[0, 1], expect[1, 0], expect[2, 2] = 1, 2, 3
    np.testing.assert_allclose(dense, expect)


def test_coo_coalesce_sums_duplicates():
    idx = np.array([[0, 0, 1], [1, 1, 0]])
    vals = np.array([1.0, 4.0, 2.0], np.float32)
    sp = sparse.sparse_coo_tensor(idx, vals, (2, 2)).coalesce()
    assert sp.nnz == 2
    assert sp.to_dense().numpy()[0, 1] == pytest.approx(5.0)


def test_csr_roundtrip_and_conversion():
    dense = np.array([[1, 0, 2], [0, 0, 3], [4, 0, 0]], np.float32)
    coo = sparse.to_sparse_coo(paddle.to_tensor(dense))
    csr = coo.to_sparse_csr()
    np.testing.assert_array_equal(np.asarray(csr.crows().numpy()),
                                  [0, 2, 3, 4])
    np.testing.assert_allclose(csr.to_dense().numpy(), dense)
    back = csr.to_sparse_coo()
    np.testing.assert_allclose(back.to_dense().numpy(), dense)


def test_sparse_elementwise_and_relu():
    d1 = np.array([[1, -2], [0, 3]], np.float32)
    d2 = np.array([[5, 1], [0, -1]], np.float32)
    s1 = sparse.to_sparse_coo(paddle.to_tensor(d1))
    s2 = sparse.to_sparse_coo(paddle.to_tensor(d2))
    np.testing.assert_allclose(sparse.add(s1, s2).to_dense().numpy(),
                               d1 + d2)
    r = sparse.relu(s1).to_dense().numpy()
    np.testing.assert_allclose(r, np.maximum(d1, 0))


def test_spmm_matches_dense():
    rng = np.random.RandomState(0)
    dense = rng.randn(6, 5).astype(np.float32)
    dense[rng.rand(6, 5) > 0.4] = 0.0
    y = rng.randn(5, 4).astype(np.float32)
    sp = sparse.to_sparse_coo(paddle.to_tensor(dense))
    out = sparse.matmul(sp, paddle.to_tensor(y)).numpy()
    np.testing.assert_allclose(out, dense @ y, rtol=1e-5, atol=1e-5)
    # CSR path
    out2 = sparse.matmul(sp.to_sparse_csr(), paddle.to_tensor(y)).numpy()
    np.testing.assert_allclose(out2, dense @ y, rtol=1e-5, atol=1e-5)


def test_masked_matmul_sddmm():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 3).astype(np.float32)
    y = rng.randn(3, 4).astype(np.float32)
    mask_d = np.zeros((4, 4), np.float32)
    mask_d[0, 1] = mask_d[2, 3] = 1
    mask = sparse.to_sparse_coo(paddle.to_tensor(mask_d))
    out = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y),
                               mask)
    full = x @ y
    got = out.to_dense().numpy()
    assert got[0, 1] == pytest.approx(full[0, 1], rel=1e-5)
    assert got[2, 3] == pytest.approx(full[2, 3], rel=1e-5)
    assert got[1, 1] == 0


def test_sparse_transpose():
    d = np.array([[1, 0], [2, 3]], np.float32)
    sp = sparse.to_sparse_coo(paddle.to_tensor(d))
    np.testing.assert_allclose(
        sparse.transpose(sp, [1, 0]).to_dense().numpy(), d.T)


# -- geometric --------------------------------------------------------------
def test_segment_ops():
    data = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]],
                                     np.float32))
    ids = np.array([0, 0, 1])
    np.testing.assert_allclose(
        geometric.segment_sum(data, ids, 2).numpy(),
        [[4, 6], [5, 6]])
    np.testing.assert_allclose(
        geometric.segment_mean(data, ids, 2).numpy(),
        [[2, 3], [5, 6]])
    np.testing.assert_allclose(
        geometric.segment_max(data, ids, 2).numpy(),
        [[3, 4], [5, 6]])
    np.testing.assert_allclose(
        geometric.segment_min(data, ids, 2).numpy(),
        [[1, 2], [5, 6]])


def test_send_u_recv_sum_and_mean():
    x = paddle.to_tensor(np.array([[1.], [2.], [3.]], np.float32))
    src = np.array([0, 1, 2, 0])
    dst = np.array([1, 2, 0, 2])
    out = geometric.send_u_recv(x, src, dst, "sum").numpy()
    np.testing.assert_allclose(out, [[3], [1], [3]])
    out_mean = geometric.send_u_recv(x, src, dst, "mean").numpy()
    np.testing.assert_allclose(out_mean, [[3], [1], [1.5]])


def test_send_u_recv_max_empty_segment_zero():
    x = paddle.to_tensor(np.array([[1.], [5.]], np.float32))
    src = np.array([0])
    dst = np.array([0])
    out = geometric.send_u_recv(x, src, dst, "max", out_size=2).numpy()
    np.testing.assert_allclose(out, [[1], [0]])   # node 1: no in-edges → 0


def test_send_ue_recv():
    x = paddle.to_tensor(np.array([[1.], [2.]], np.float32))
    e = paddle.to_tensor(np.array([[10.], [20.]], np.float32))
    src = np.array([0, 1])
    dst = np.array([1, 0])
    out = geometric.send_ue_recv(x, e, src, dst, "add", "sum").numpy()
    np.testing.assert_allclose(out, [[22], [11]])
    out2 = geometric.send_ue_recv(x, e, src, dst, "mul", "sum").numpy()
    np.testing.assert_allclose(out2, [[40], [10]])


def test_sample_neighbors():
    # CSC: node0 ← {1,2}, node1 ← {0}, node2 ← {0,1}
    row = np.array([1, 2, 0, 0, 1])
    colptr = np.array([0, 2, 3, 5])
    neigh, counts = geometric.sample_neighbors(row, colptr,
                                               np.array([0, 2]))
    assert list(counts.numpy()) == [2, 2]
    assert set(np.asarray(neigh.numpy())[:2]) == {1, 2}
    neigh2, counts2 = geometric.sample_neighbors(
        row, colptr, np.array([0]), sample_size=1)
    assert list(counts2.numpy()) == [1]
    assert int(neigh2.numpy()[0]) in (1, 2)


def test_weighted_sample_neighbors_respects_weights():
    # node 0 has neighbors {1, 2}: weight(edge to 1) >> weight(edge to 2)
    row = np.array([1, 2, 0, 0, 1])
    colptr = np.array([0, 2, 3, 5])
    w = np.array([1e6, 1e-6, 1.0, 1.0, 1.0], np.float32)
    hits = 0
    for _ in range(20):
        neigh, counts = geometric.weighted_sample_neighbors(
            row, colptr, w, np.array([0]), sample_size=1)
        assert list(counts.numpy()) == [1]
        hits += int(neigh.numpy()[0] == 1)
    assert hits >= 18    # p(pick 2) ~ 1e-12 per draw

    # sample_size=-1 returns everything + eids
    neigh, counts, eids = geometric.weighted_sample_neighbors(
        row, colptr, w, np.array([0, 2]), return_eids=True,
        eids=np.arange(5))
    assert list(counts.numpy()) == [2, 2]
    assert set(eids.numpy().tolist()) == {0, 1, 3, 4}


def test_reindex_graph_reference_example():
    """Exact example from the reference docstring (reindex.py:51)."""
    x = np.array([0, 1, 2], np.int64)
    neighbors = np.array([8, 9, 0, 4, 7, 6, 7], np.int64)
    count = np.array([2, 3, 2], np.int32)
    src, dst, out_nodes = geometric.reindex_graph(x, neighbors, count)
    np.testing.assert_array_equal(src.numpy(), [3, 4, 0, 5, 6, 7, 6])
    np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1, 1, 2, 2])
    np.testing.assert_array_equal(out_nodes.numpy(),
                                  [0, 1, 2, 8, 9, 4, 7, 6])


def test_reindex_heter_graph_reference_example():
    """Exact example from the reference docstring (reindex.py:170)."""
    x = np.array([0, 1, 2], np.int64)
    na = np.array([8, 9, 0, 4, 7, 6, 7], np.int64)
    ca = np.array([2, 3, 2], np.int32)
    nb = np.array([0, 2, 3, 5, 1], np.int64)
    cb = np.array([1, 3, 1], np.int32)
    src, dst, out_nodes = geometric.reindex_heter_graph(
        x, [na, nb], [ca, cb])
    np.testing.assert_array_equal(
        src.numpy(), [3, 4, 0, 5, 6, 7, 6, 0, 2, 8, 9, 1])
    np.testing.assert_array_equal(
        dst.numpy(), [0, 0, 1, 1, 1, 2, 2, 0, 1, 1, 1, 2])
    np.testing.assert_array_equal(out_nodes.numpy(),
                                  [0, 1, 2, 8, 9, 4, 7, 6, 3, 5])


def test_graph_khop_sampler_two_layers():
    # chain graph in CSC: 0 <- 1 <- 2 <- 3 (node i's neighbor is i+1)
    row = np.array([1, 2, 3], np.int64)
    colptr = np.array([0, 1, 2, 3, 3], np.int64)
    src, dst, sample_index, reindex_nodes = geometric.graph_khop_sampler(
        row, colptr, np.array([0], np.int64), sample_sizes=[1, 1])
    # layer 1: 0 <- 1; layer 2: 1 <- 2
    np.testing.assert_array_equal(sample_index.numpy(), [0, 1, 2])
    np.testing.assert_array_equal(reindex_nodes.numpy(), [0])
    assert src.numpy().shape == (2, 1)
    np.testing.assert_array_equal(src.numpy().ravel(), [1, 2])
    np.testing.assert_array_equal(dst.numpy().ravel(), [0, 1])
    # eids path
    *_, eids = geometric.graph_khop_sampler(
        row, colptr, np.array([0], np.int64), sample_sizes=[1, 1],
        sorted_eids=np.arange(3), return_eids=True)
    np.testing.assert_array_equal(np.sort(eids.numpy().ravel()), [0, 1])


def test_graph_khop_sampler_diamond_no_duplicate_expansion():
    """Review regression: a node reached from multiple parents in one
    layer must be expanded once, not once per parent."""
    row = np.array([2, 2, 3], np.int64)
    colptr = np.array([0, 1, 2, 3, 3], np.int64)
    src, dst, sample_index, _ = geometric.graph_khop_sampler(
        row, colptr, np.array([0, 1], np.int64), sample_sizes=[-1, -1])
    np.testing.assert_array_equal(src.numpy().ravel(), [2, 2, 3])
    np.testing.assert_array_equal(dst.numpy().ravel(), [0, 1, 2])
    np.testing.assert_array_equal(sample_index.numpy(), [0, 1, 2, 3])


def test_message_passing_gradients_flow():
    """Regression: geometric/sparse ops must record GradNodes so upstream
    layers train."""
    import paddle_tpu.nn as nn
    lin = nn.Linear(3, 3)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 3)
                         .astype(np.float32))
    src = np.array([0, 1, 2, 3])
    dst = np.array([1, 2, 3, 0])
    h = lin(x)
    agg = geometric.send_u_recv(h, src, dst, "sum")
    agg.sum().backward()
    assert lin.weight.grad is not None
    assert float(np.abs(np.asarray(lin.weight.grad.numpy())).sum()) > 0


def test_sparse_matmul_gradient_to_dense_operand():
    dense = np.array([[1., 0.], [0., 2.]], np.float32)
    sp = sparse.to_sparse_coo(paddle.to_tensor(dense))
    y = paddle.to_tensor(np.ones((2, 3), np.float32))
    y.stop_gradient = False
    out = sparse.matmul(sp, y)
    out.sum().backward()
    # d(sum)/dy = sp^T @ ones = column sums of sp rows
    np.testing.assert_allclose(y.grad.numpy(),
                               dense.T @ np.ones((2, 3), np.float32))


def test_to_sparse_coo_partial_dim_no_duplicates():
    v = np.array([[1., 2.]], np.float32)   # one row, trailing dim dense
    sp = sparse.to_sparse_coo(paddle.to_tensor(v), sparse_dim=1)
    assert sp.nnz == 1
    np.testing.assert_allclose(np.asarray(sp.values().numpy()), [[1., 2.]])


def test_gcn_layer_end_to_end():
    """Mini GCN aggregation: normalize-by-degree message passing."""
    n = 4
    edges = np.array([[0, 1], [1, 2], [2, 3], [3, 0], [1, 0]])
    src, dst = edges[:, 0], edges[:, 1]
    x = paddle.to_tensor(np.eye(n, dtype=np.float32))
    agg = geometric.send_u_recv(x, src, dst, "mean", out_size=n)
    assert agg.numpy().shape == (n, n)
    assert np.isfinite(agg.numpy()).all()


# -- sparse op-surface expansion (round 2) ----------------------------------
class TestSparseUnaryBinary:
    def _coo(self, rng, shape=(4, 6), density=0.4):
        d = (rng.randn(*shape) * (rng.rand(*shape) < density)) \
            .astype(np.float32)
        return d, sparse.to_sparse_coo(paddle.to_tensor(d))

    def test_unary_value_ops(self):
        rng = np.random.RandomState(0)
        d, s = self._coo(rng)
        for name, ref in [("sin", np.sin), ("tanh", np.tanh),
                          ("square", np.square), ("expm1", np.expm1),
                          ("abs", np.abs), ("neg", np.negative),
                          ("rad2deg", np.rad2deg),
                          ("relu6", lambda v: np.clip(v, 0, 6))]:
            out = getattr(sparse, name)(s).to_dense()
            np.testing.assert_allclose(np.asarray(out), ref(d), atol=1e-5,
                                       err_msg=name)

    def test_unary_preserves_csr_layout(self):
        rng = np.random.RandomState(1)
        d, s = self._coo(rng)
        csr = s.to_sparse_csr()
        out = sparse.tanh(csr)
        assert isinstance(out, sparse.SparseCsrTensor)
        np.testing.assert_allclose(np.asarray(out.to_dense()),
                                   np.tanh(d), atol=1e-5)

    def test_softmax_active_entries_only(self):
        rng = np.random.RandomState(2)
        d, s = self._coo(rng)
        dd = np.asarray(sparse.softmax(s).to_dense())
        for r in range(d.shape[0]):
            nz = d[r] != 0
            if nz.sum():
                e = np.exp(d[r][nz] - d[r][nz].max())
                np.testing.assert_allclose(dd[r][nz], e / e.sum(),
                                           atol=1e-5)
        # CSR path agrees
        dd2 = np.asarray(sparse.softmax(s.to_sparse_csr()).to_dense())
        np.testing.assert_allclose(dd2, dd, atol=1e-6)

    def test_sum_axes(self):
        rng = np.random.RandomState(3)
        d, s = self._coo(rng)
        assert abs(float(np.asarray(sparse.sum(s))) - d.sum()) < 1e-4
        np.testing.assert_allclose(
            np.asarray(sparse.sum(s, axis=0).to_dense()), d.sum(0),
            atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(sparse.sum(s, axis=1, keepdim=True).to_dense()),
            d.sum(1, keepdims=True), atol=1e-5)

    def test_reshape_slice_mask_mv_addmm(self):
        rng = np.random.RandomState(4)
        d, s = self._coo(rng)
        np.testing.assert_allclose(
            np.asarray(sparse.reshape(s, [2, 12]).to_dense()),
            d.reshape(2, 12), atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sparse.slice(s, [0, 1], [1, 2], [3, 5]).to_dense()),
            d[1:3, 2:5], atol=1e-6)
        m = sparse.mask_as(paddle.to_tensor(np.ones((4, 6), np.float32)), s)
        assert m.nnz == s.coalesce().nnz
        vec = rng.randn(6).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(sparse.mv(s, paddle.to_tensor(vec))), d @ vec,
            atol=1e-4)
        inp = rng.randn(4, 3).astype(np.float32)
        y = rng.randn(6, 3).astype(np.float32)
        am = sparse.addmm(paddle.to_tensor(inp), s, paddle.to_tensor(y),
                          beta=0.5, alpha=2.0)
        np.testing.assert_allclose(np.asarray(am), 0.5 * inp + 2 * (d @ y),
                                   atol=1e-4)

    def test_subtract_divide_same_pattern(self):
        rng = np.random.RandomState(5)
        d, s = self._coo(rng)
        s2 = sparse.mask_as(paddle.to_tensor((d * 3).astype(np.float32)), s)
        np.testing.assert_allclose(
            np.asarray(sparse.subtract(s2, s).to_dense()), d * 2, atol=1e-5)


class TestSparseConvPool:
    @pytest.mark.slow
    def test_conv2d_matches_dense_at_active_sites(self):
        import jax.numpy as jnp
        from jax import lax
        import paddle_tpu.sparse.nn.functional as SF

        rng = np.random.RandomState(1)
        N, H, W, C, Co, K = 2, 6, 6, 3, 5, 3
        mask = rng.rand(N, H, W) > 0.7
        dense = (rng.randn(N, H, W, C) * mask[..., None]).astype(np.float32)
        idx = np.stack(np.nonzero(mask)).astype(np.int32)
        x = sparse.SparseCooTensor(idx, dense[tuple(idx)], (N, H, W),
                                   coalesced=True)
        w = (rng.randn(K, K, C, Co) * 0.1).astype(np.float32)
        b = (rng.randn(Co) * 0.1).astype(np.float32)
        for stride in (1, 2):
            out = SF.conv2d(x, w, b if stride == 1 else None,
                            stride=stride, padding=1)
            ref = np.asarray(lax.conv_general_dilated(
                jnp.asarray(dense), jnp.asarray(w), (stride, stride),
                [(1, 1), (1, 1)],
                dimension_numbers=("NHWC", "HWIO", "NHWC")))
            if stride == 1:
                ref = ref + b
            od = np.asarray(out.to_dense())
            oi = np.asarray(out._indices)
            for t in range(oi.shape[1]):
                n, h, wx = oi[:, t]
                np.testing.assert_allclose(od[n, h, wx], ref[n, h, wx],
                                           atol=1e-4)

    def test_subm_conv_preserves_pattern(self):
        import paddle_tpu.sparse.nn.functional as SF
        rng = np.random.RandomState(2)
        mask = rng.rand(1, 5, 5) > 0.6
        dense = (rng.randn(1, 5, 5, 2) * mask[..., None]).astype(np.float32)
        idx = np.stack(np.nonzero(mask)).astype(np.int32)
        x = sparse.SparseCooTensor(idx, dense[tuple(idx)], (1, 5, 5),
                                   coalesced=True)
        w = rng.randn(3, 3, 2, 4).astype(np.float32)
        out = SF.subm_conv2d(x, w, padding=1)
        assert np.asarray(out._indices).shape == idx.shape

    def test_max_pool3d(self):
        import paddle_tpu.sparse.nn.functional as SF
        rng = np.random.RandomState(3)
        N, D, H, W, C = 2, 4, 4, 4, 3
        m = rng.rand(N, D, H, W) > 0.6
        dn = (rng.randn(N, D, H, W, C) * m[..., None]).astype(np.float32)
        i3 = np.stack(np.nonzero(m)).astype(np.int32)
        x = sparse.SparseCooTensor(i3, dn[tuple(i3)], (N, D, H, W),
                                   coalesced=True)
        p = SF.max_pool3d(x, 2, 2)
        pi = np.asarray(p._indices)
        pv = np.asarray(p.values().numpy())
        for t in range(pi.shape[1]):
            n, dz, h, wx = pi[:, t]
            win = dn[n, dz*2:dz*2+2, h*2:h*2+2, wx*2:wx*2+2]
            winm = m[n, dz*2:dz*2+2, h*2:h*2+2, wx*2:wx*2+2]
            np.testing.assert_allclose(pv[t], win[winm].max(axis=0),
                                       atol=1e-5)

    @pytest.mark.slow
    def test_layer_chain_and_batchnorm(self):
        import paddle_tpu.sparse.nn as snn
        rng = np.random.RandomState(4)
        m = rng.rand(2, 4, 4, 4) > 0.6
        dn = (rng.randn(2, 4, 4, 4, 3) * m[..., None]).astype(np.float32)
        i3 = np.stack(np.nonzero(m)).astype(np.int32)
        x = sparse.SparseCooTensor(i3, dn[tuple(i3)], (2, 4, 4, 4),
                                   coalesced=True)
        conv = snn.SubmConv3D(3, 8, 3, padding=1)
        bn = snn.BatchNorm(8)
        bn.train()
        out = snn.ReLU()(bn(conv(x)))
        v = np.asarray(out.values().numpy())
        assert v.min() >= 0 and v.shape[1] == 8
        # eval path uses running stats
        bn.eval()
        out2 = bn(conv(x))
        assert np.asarray(out2.values().numpy()).shape == v.shape
        # convert_sync_batchnorm
        sync = snn.SyncBatchNorm.convert_sync_batchnorm(bn)
        assert isinstance(sync, snn.SyncBatchNorm)


class TestSparseAttention:
    def test_full_mask_matches_dense(self):
        import paddle_tpu.sparse.nn.functional as SF
        rng = np.random.RandomState(5)
        B, Hh, S, Dd = 2, 2, 8, 4
        q, k, v = (rng.randn(B, Hh, S, Dd).astype(np.float32)
                   for _ in range(3))
        ii = np.stack(np.meshgrid(np.arange(B * Hh), np.arange(S),
                                  np.arange(S), indexing="ij"), 0) \
            .reshape(3, -1).astype(np.int32)
        mask = sparse.SparseCooTensor(ii, np.ones(ii.shape[1], np.float32),
                                      (B * Hh, S, S), coalesced=True)
        out = np.asarray(SF.attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            mask).numpy())
        att = np.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(Dd)
        att = np.exp(att - att.max(-1, keepdims=True))
        att /= att.sum(-1, keepdims=True)
        ref = np.einsum("bhst,bhtd->bhsd", att, v)
        np.testing.assert_allclose(out, ref, atol=1e-4)

    def test_causal_mask_zeroes_future(self):
        import paddle_tpu.sparse.nn.functional as SF
        rng = np.random.RandomState(6)
        B, Hh, S, Dd = 1, 1, 6, 4
        q, k, v = (rng.randn(B, Hh, S, Dd).astype(np.float32)
                   for _ in range(3))
        rows, cols = np.tril_indices(S)
        ii = np.stack([np.zeros_like(rows), rows, cols]).astype(np.int32)
        mask = sparse.SparseCooTensor(ii, np.ones(len(rows), np.float32),
                                      (1, S, S), coalesced=True)
        out = np.asarray(SF.attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            mask).numpy())
        att = np.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(Dd)
        att = np.where(np.tril(np.ones((S, S))) > 0, att, -np.inf)
        att = np.exp(att - att.max(-1, keepdims=True))
        att /= att.sum(-1, keepdims=True)
        ref = np.einsum("bhst,bhtd->bhsd", att, v)
        np.testing.assert_allclose(out, ref, atol=1e-4)


class TestSparseCastAndBatchedCsr:
    def test_cast_value_and_index_dtype(self):
        rng = np.random.RandomState(0)
        d = (rng.randn(3, 4) * (rng.rand(3, 4) < 0.5)).astype(np.float32)
        s = sparse.to_sparse_coo(paddle.to_tensor(d))
        c = sparse.cast(s, value_dtype="float64", index_dtype="int64")
        assert str(c._values.dtype) in ("float64", "float32")  # x64 flag
        csr = s.to_sparse_csr()
        c2 = sparse.cast(csr, value_dtype="float32")
        assert isinstance(c2, sparse.SparseCsrTensor)

    def test_batched_csr_roundtrip(self):
        rng = np.random.RandomState(1)
        B, S = 3, 5
        m = rng.rand(B, S, S) > 0.5
        dn = (rng.randn(B, S, S) * m).astype(np.float32)
        coo = sparse.to_sparse_coo(paddle.to_tensor(dn))
        csr = coo.to_sparse_csr()
        assert np.asarray(csr.crows().numpy()).shape == (B * (S + 1),)
        np.testing.assert_allclose(np.asarray(csr.to_dense()), dn)
        np.testing.assert_allclose(
            np.asarray(csr.to_sparse_coo().to_dense()), dn)

    def test_attention_accepts_csr_mask(self):
        import paddle_tpu.sparse.nn.functional as SF
        rng = np.random.RandomState(2)
        B, H, S, D = 2, 2, 6, 4
        q, k, v = (rng.randn(B, H, S, D).astype(np.float32)
                   for _ in range(3))
        full = np.ones((B * H, S, S), np.float32)
        mcoo = sparse.to_sparse_coo(paddle.to_tensor(full))
        o1 = np.asarray(SF.attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            mcoo).numpy())
        o2 = np.asarray(SF.attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            mcoo.to_sparse_csr()).numpy())
        np.testing.assert_allclose(o1, o2, atol=1e-5)


def test_batched_csr_softmax_and_mask_as_match_coo():
    rng = np.random.RandomState(7)
    B, S = 2, 4
    m = rng.rand(B, S, S) > 0.4
    dn = (rng.randn(B, S, S) * m).astype(np.float32)
    coo = sparse.to_sparse_coo(paddle.to_tensor(dn))
    csr = coo.to_sparse_csr()
    np.testing.assert_allclose(
        np.asarray(sparse.softmax(csr).to_dense()),
        np.asarray(sparse.softmax(coo).to_dense()), atol=1e-5)
    mk = sparse.mask_as(paddle.to_tensor(dn * 7), csr)
    np.testing.assert_allclose(np.asarray(mk.to_dense()), dn * 7,
                               atol=1e-5)
