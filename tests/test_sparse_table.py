"""ShardedSparseTable — the TPU-native parameter-server analog
(reference: paddle.static.nn.sparse_embedding + distributed/ps
SparseTable sparse push/pull; entry_attr.py CountFilterEntry)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from paddle_tpu.distributed.fleet import (CountFilterEntry,
                                          ShardedSparseTable, dedupe_sum)


def _mesh(n=8, axis="mp"):
    return Mesh(np.array(jax.devices()[:n]), (axis,))


def test_dedupe_sum_merges_duplicates():
    ids = jnp.asarray([5, 2, 5, 7, 2, 5], jnp.int32)
    g = jnp.arange(6 * 3, dtype=jnp.float32).reshape(6, 3)
    ids_u, g_u = dedupe_sum(ids, g)
    got = {}
    for i in range(6):
        rid = int(ids_u[i])
        v = np.asarray(g_u[i])
        if v.any():
            got[rid] = got.get(rid, 0) + v
    want = {}
    for i, rid in enumerate([5, 2, 5, 7, 2, 5]):
        want[rid] = want.get(rid, 0) + np.asarray(g[i])
    for rid, v in want.items():
        np.testing.assert_allclose(got[rid], v, rtol=1e-6)


def test_lookup_and_padding_row():
    mesh = _mesh()
    t = ShardedSparseTable(64, 16, mesh, optimizer="sgd", padding_idx=0)
    ids = jnp.asarray([[1, 0], [63, 7]], jnp.int32)
    out = t.lookup(t.weight, ids)
    assert out.shape == (2, 2, 16)
    np.testing.assert_array_equal(np.asarray(out[0, 1]), 0.0)
    np.testing.assert_allclose(np.asarray(out[0, 0]),
                               np.asarray(t.weight[1]))


def test_sparse_sgd_matches_dense_with_duplicates():
    """Sparse push with duplicate ids == dense embedding grad descent
    (duplicates sum — the PS sparse-push contract)."""
    mesh = _mesh()
    t = ShardedSparseTable(32, 8, mesh, optimizer="sgd", lr=0.1)
    w0 = np.asarray(t.weight).copy()
    ids = jnp.asarray([3, 9, 3, 3, 20], jnp.int32)
    tgt = jnp.asarray(np.random.RandomState(0).randn(5, 8), jnp.float32)

    def loss_fn(rows):
        return jnp.mean((rows - tgt) ** 2)

    loss, w1, _ = t.grad_and_update(t.weight, t.accum, ids, loss_fn)

    # dense reference: full-table embedding, same loss, plain SGD
    def dense_loss(w):
        return jnp.mean((jnp.take(w, ids, axis=0) - tgt) ** 2)
    gw = jax.grad(dense_loss)(jnp.asarray(w0))
    w_ref = np.asarray(w0 - 0.1 * gw)
    np.testing.assert_allclose(np.asarray(w1), w_ref, atol=1e-6)
    # untouched rows bit-identical
    untouched = [i for i in range(32) if i not in (3, 9, 20)]
    np.testing.assert_array_equal(np.asarray(w1)[untouched],
                                  w0[untouched])


def test_sparse_adagrad_accumulates_per_row():
    mesh = _mesh()
    t = ShardedSparseTable(16, 4, mesh, optimizer="adagrad", lr=0.5)
    ids = jnp.asarray([2, 5, 2], jnp.int32)
    g = jnp.ones((3, 4), jnp.float32)
    w1, acc1 = t.apply_sparse_grad(t.weight, t.accum, ids, g)
    # row 2 sees the SUMMED gradient (2.0 per element) once
    gsum_row2 = 4 * (2.0 ** 2)     # |g|^2 of the summed grad
    assert float(acc1[2]) == pytest.approx(gsum_row2)
    assert float(acc1[5]) == pytest.approx(4 * 1.0)
    assert float(acc1[7]) == 0.0
    step2 = 0.5 / np.sqrt(gsum_row2 + 1e-10) * 2.0
    np.testing.assert_allclose(np.asarray(t.weight[2] - w1[2]),
                               np.full((4,), step2), rtol=1e-5)


def test_adagrad_row0_with_duplicates_not_corrupted():
    """Regression: dedupe padding slots point at row 0; the accumulator
    scatter must be an ADD of exact zeros, never a repeated-index SET
    racing stale vs fresh values — a batch containing real id 0 plus
    duplicates of another id hits exactly that pattern."""
    mesh = _mesh()
    t = ShardedSparseTable(16, 4, mesh, optimizer="adagrad", lr=0.5)
    ids = jnp.asarray([0, 7, 7], jnp.int32)
    g = jnp.ones((3, 4), jnp.float32)
    _, acc1 = t.apply_sparse_grad(t.weight, t.accum, ids, g)
    assert float(acc1[0]) == pytest.approx(4 * 1.0)   # row 0 kept
    assert float(acc1[7]) == pytest.approx(4 * 4.0)   # summed dup grad


def test_probability_entry_and_top_level_exports():
    import paddle_tpu.distributed as dist
    assert dist.CountFilterEntry is CountFilterEntry
    assert "ShardedSparseTable" in dist.__all__
    mesh = _mesh()
    t = ShardedSparseTable(16, 4, mesh, optimizer="sgd",
                           entry=dist.ProbabilityEntry(1.0))
    ids = jnp.asarray([3], jnp.int32)
    out = t.lookup(t.weight, ids, t.counts)
    np.testing.assert_array_equal(np.asarray(out), 0.0)
    with pytest.raises(ValueError, match="PRNG key"):
        t.observe(t.counts, ids)   # implicit key would bake into jit
    counts = t.observe(t.counts, ids, key=jax.random.PRNGKey(0))
    out = t.lookup(t.weight, ids, counts)   # p=1.0: admitted first show
    assert np.abs(np.asarray(out)).sum() > 0
    with pytest.raises(ValueError):
        dist.ProbabilityEntry(0.0)


def test_gated_rows_get_no_push_until_admitted():
    """Reference PS semantics: a non-admitted row receives NO optimizer
    push — its embedding and Adagrad state stay pristine until the
    admission threshold is crossed."""
    mesh = _mesh()
    t = ShardedSparseTable(16, 4, mesh, optimizer="adagrad", lr=0.5,
                           entry=CountFilterEntry(2))
    ids = jnp.asarray([3], jnp.int32)
    tgt = jnp.ones((1, 4), jnp.float32)
    counts = t.observe(t.counts, ids)      # count 1 < 2: still gated

    def loss_fn(rows):
        return jnp.mean((rows - tgt) ** 2)

    _, w1, a1 = t.grad_and_update(t.weight, t.accum, ids, loss_fn,
                                  counts=counts)
    np.testing.assert_array_equal(np.asarray(w1[3]),
                                  np.asarray(t.weight[3]))
    assert float(a1[3]) == 0.0
    counts = t.observe(counts, ids)        # count 2: admitted
    _, w2, a2 = t.grad_and_update(w1, a1, ids, loss_fn, counts=counts)
    assert np.abs(np.asarray(w2[3] - w1[3])).sum() > 0
    assert float(a2[3]) > 0.0
    # entry table without counts must fail loudly, not silently gate
    with pytest.raises(ValueError, match="counts"):
        t.grad_and_update(w2, a2, ids, loss_fn)


def test_entry_gating_admits_after_threshold():
    mesh = _mesh()
    t = ShardedSparseTable(16, 4, mesh, optimizer="sgd",
                           entry=CountFilterEntry(2))
    ids = jnp.asarray([3], jnp.int32)
    out = t.lookup(t.weight, ids, t.counts)
    np.testing.assert_array_equal(np.asarray(out), 0.0)  # unseen: gated
    counts = t.observe(t.counts, ids)
    out = t.lookup(t.weight, ids, counts)
    np.testing.assert_array_equal(np.asarray(out), 0.0)  # count 1 < 2
    counts = t.observe(counts, ids)
    out = t.lookup(t.weight, ids, counts)
    assert np.abs(np.asarray(out)).sum() > 0              # admitted


def test_sharded_update_under_jit_matches_single_device():
    """The whole pull->loss->push cycle jitted over the 8-device mesh
    must equal the 1-device result (GSPMD moves rows, math unchanged)."""
    ids = jnp.asarray([4, 11, 4, 30], jnp.int32)
    tgt = jnp.asarray(np.random.RandomState(1).randn(4, 8), jnp.float32)

    results = {}
    for n in (1, 8):
        mesh = _mesh(n)
        t = ShardedSparseTable(32, 8, mesh, optimizer="adagrad", lr=0.2,
                               seed=7)

        @jax.jit
        def train2(w, a):
            def loss_fn(rows):
                return jnp.mean((rows - tgt) ** 2)
            l1, w, a = t.grad_and_update(w, a, ids, loss_fn)
            l2, w, a = t.grad_and_update(w, a, ids, loss_fn)
            return l1, l2, w, a

        with mesh:
            l1, l2, w, a = train2(t.weight, t.accum)
        results[n] = (float(l1), float(l2), np.asarray(w), np.asarray(a))
    assert results[1][1] < results[1][0]   # loss descends
    np.testing.assert_allclose(results[8][2], results[1][2], atol=1e-6)
    np.testing.assert_allclose(results[8][3], results[1][3], atol=1e-6)
    assert results[8][0] == pytest.approx(results[1][0])


def test_state_dict_roundtrip_through_dist_checkpoint(tmp_path):
    """Table + accumulators ride the distributed checkpoint (the PS
    snapshot analog), resharding 8 -> 4 devices on load."""
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed.checkpoint.save_load import (
        load_state_dict, save_state_dict)

    t = ShardedSparseTable(32, 8, _mesh(8), optimizer="adagrad")
    ids = jnp.asarray([1, 2, 3], jnp.int32)
    w, a = t.apply_sparse_grad(t.weight, t.accum, ids,
                               jnp.ones((3, 8), jnp.float32))
    save_state_dict({"weight": Tensor(w), "accum": Tensor(a)},
                    str(tmp_path))
    t2 = ShardedSparseTable(32, 8, _mesh(4), optimizer="adagrad", seed=9)
    st = {"weight": Tensor(t2.weight), "accum": Tensor(t2.accum)}
    load_state_dict(st, str(tmp_path))
    np.testing.assert_allclose(np.asarray(st["weight"]._value),
                               np.asarray(w), atol=1e-7)
    np.testing.assert_allclose(np.asarray(st["accum"]._value),
                               np.asarray(a), atol=1e-7)
