"""Continuous telemetry plane (r22): time-series sampling over the
``metrics()`` protocol, counter->rate derivation, OpenMetrics
exposition + lint, deterministic fake-clock SLO burn-rate alerting,
robust (median+MAD) anomaly detectors wired into the timeline ring and
the flight-recorder stall dumps, JSONL banking with rotation, and
``tools/telemetry_summary.py``."""
import json
import os
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.models import llama
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.inference.generation import GenerationConfig
from paddle_tpu.observability import (Observability, TelemetryConfig,
                                      TelemetryPlane, flatten_metrics,
                                      lint_exposition,
                                      render_exposition)

pytestmark = pytest.mark.telemetry

CFG = llama.LlamaConfig(vocab_size=97, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        max_position_embeddings=128, dtype=jnp.float32,
                        remat=False)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _engine(params, **kw):
    kw.setdefault("capacity", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("max_seq_len", 64)
    return ServingEngine(params, CFG, **kw)


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _plane(clock, **kw):
    kw.setdefault("sample_every", 1)
    kw.setdefault("detectors", ())
    kw.setdefault("clock", clock)
    alerts = []
    plane = TelemetryPlane(TelemetryConfig(**kw),
                           on_alert=alerts.append)
    return plane, alerts


# -- flattening --------------------------------------------------------

def test_flatten_paths_labels_and_leaf_filtering():
    tree = {
        "tokens": 7, "ratio": 0.5, "name": "fused",   # str dropped
        "flag": True,                                  # bool dropped
        "nan": float("nan"),                           # non-finite drop
        "nested": {"a": {"b": 1}},
        "scheduler": {"per_class": {"0": {"admitted": 3}},
                      "queue_depth": 2},
        "routing": {"per_replica": {"r0": {"queue_depth": 1}}},
        "telemetry": {"samples": 9},                   # always skipped
        "groups": {"x": 1},
    }
    rows = flatten_metrics(tree, skip=("groups",))
    got = {(p, labels): v for p, labels, v in rows}
    assert got[("tokens", ())] == 7.0
    assert got[("ratio", ())] == 0.5
    assert got[("nested.a.b", ())] == 1.0
    assert got[("scheduler.queue_depth", ())] == 2.0
    # per_class / per_replica keys lift into labels, path keeps segment
    assert got[("scheduler.per_class.admitted",
                (("cls", "0"),))] == 3.0
    assert got[("routing.per_replica.queue_depth",
                (("replica", "r0"),))] == 1.0
    paths = {p for p, _, _ in rows}
    assert not any(p.startswith(("telemetry", "groups", "name",
                                 "flag", "nan")) for p in paths)


# -- sampling + counter->rate ------------------------------------------

def test_counter_rate_derivation_and_reset_skip():
    clk = _FakeClock()
    plane, _ = _plane(clk)
    src = {"tokens": 0, "depth": 5}
    plane.register("eng", lambda: dict(src), counters={"tokens": 0})
    for dt, tok in ((0.0, 0), (1.0, 10), (1.0, 30), (2.0, 30)):
        clk.t += dt
        src["tokens"] = tok
        plane.sample()
    series = {s.path: s for s in plane.series()}
    assert series["tokens"].kind == "counter"
    assert series["depth"].kind == "gauge"       # not in counters dict
    rates = series["tokens_per_s"].values()
    assert rates == [10.0, 20.0, 0.0]
    # counter reset (reset_metrics): negative delta derives NO rate
    src["tokens"] = 0
    clk.t += 1.0
    plane.sample()
    assert series["tokens_per_s"].values() == [10.0, 20.0, 0.0]
    # series are bounded deques
    assert series["tokens"].samples.maxlen == \
        plane.config.series_capacity


def test_on_step_cadence():
    clk = _FakeClock()
    plane, _ = _plane(clk, sample_every=4)
    plane.register("x", lambda: {"v": 1})
    for _ in range(9):
        clk.t += 1.0
        plane.on_step()
    assert plane.snapshot()["samples"] == 2


# -- OpenMetrics exposition + lint -------------------------------------

def test_exposition_lint_clean_and_hostile_keys_sanitized():
    clk = _FakeClock()
    plane, _ = _plane(clk, namespace="paddle_tpu")
    # a hostile metric key (the r9 collective idiom) must sanitize,
    # not ship an unscrapeable exposition
    plane.register("eng", lambda: {
        "collective_psum@tp_ms": 1.5,
        "latency": {"ttft_ms": {"p95": 3.25}},
        "requests": 4,
    }, labels={"replica": "r0"}, counters={"requests": 0})
    clk.t = 1.0
    plane.sample()
    clk.t = 2.0
    plane.sample()
    text = plane.expose()
    assert lint_exposition(text) == []
    assert "paddle_tpu_collective_psum_tp_ms" in text
    assert "@" not in text.replace("# HELP", "").split("# EOF")[0] \
        .replace("collective_psum@tp_ms", "")
    assert 'component="eng"' in text and 'replica="r0"' in text
    assert "paddle_tpu_requests_total" in text     # counter suffix
    assert text.rstrip().endswith("# EOF")


def test_lint_catches_broken_expositions():
    assert lint_exposition("") != []               # no EOF
    bad_name = ("# HELP bad@name x\n# TYPE bad@name gauge\n"
                "bad@name 1\n# EOF\n")
    assert any("invalid metric name" in p
               for p in lint_exposition(bad_name))
    untyped = "orphan_metric 1\n# EOF\n"
    assert any("before TYPE" in p for p in lint_exposition(untyped))
    uncounted = ("# HELP c_thing x\n# TYPE c_thing counter\n"
                 "c_thing 1\n# EOF\n")
    assert any("_total" in p for p in lint_exposition(uncounted))
    bad_label = ('# HELP m x\n# TYPE m gauge\nm{bad-label="1"} 1\n'
                 "# EOF\n")
    assert any("invalid label name" in p
               for p in lint_exposition(bad_label))


def test_render_exposition_escapes_label_values():
    clk = _FakeClock()
    plane, _ = _plane(clk)
    plane.register("eng", lambda: {"v": 1},
                   labels={"cls": 'quo"te\\back'})
    plane.sample()
    text = render_exposition(plane.series())
    assert lint_exposition(text) == []
    assert '\\"' in text and "\\\\" in text


# -- SLO burn-rate alerting (deterministic fake clock) -----------------

def _slo_source():
    return {"scheduler": {"slo_seen": 0, "slo_attained": 0}}


def test_burn_rate_silent_on_clean_and_idle_streams():
    clk = _FakeClock()
    plane, alerts = _plane(clk, burn_fast_window=2, burn_slow_window=4)
    src = _slo_source()
    plane.register("eng", lambda: json.loads(json.dumps(src)))
    for _ in range(8):                      # perfect attainment
        clk.t += 1.0
        src["scheduler"]["slo_seen"] += 10
        src["scheduler"]["slo_attained"] += 10
        plane.sample()
    for _ in range(8):                      # idle: no deadline traffic
        clk.t += 1.0
        plane.sample()
    assert alerts == []
    assert plane.snapshot()["alerts"] == {"page": 0, "ticket": 0}


def test_burn_rate_page_on_hard_degradation():
    clk = _FakeClock()
    plane, alerts = _plane(clk, burn_fast_window=2, burn_slow_window=4,
                           slo_target=0.99, page_burn_rate=14.4)
    src = _slo_source()
    plane.register("eng", lambda: json.loads(json.dumps(src)))
    for _ in range(4):                      # clean baseline
        clk.t += 1.0
        src["scheduler"]["slo_seen"] += 10
        src["scheduler"]["slo_attained"] += 10
        plane.sample()
    for _ in range(4):                      # 100% misses: burn = 100
        clk.t += 1.0
        src["scheduler"]["slo_seen"] += 10
        plane.sample()
    pages = [a for a in alerts if a["severity"] == "page"]
    assert pages and pages[0]["rule"] == "slo_burn_rate"
    assert pages[0]["value"] >= 14.4
    assert pages[0]["threshold"] == 14.4
    # cooldown: one fire, not one per sample
    assert len(pages) == 1


def test_burn_rate_ticket_on_slow_burn():
    clk = _FakeClock()
    plane, alerts = _plane(clk, burn_fast_window=2, burn_slow_window=4,
                           slo_target=0.99)
    src = _slo_source()
    plane.register("eng", lambda: json.loads(json.dumps(src)))
    for _ in range(8):                      # steady 5% misses: burn 5
        clk.t += 1.0
        src["scheduler"]["slo_seen"] += 100
        src["scheduler"]["slo_attained"] += 95
        plane.sample()
    sevs = {a["severity"] for a in alerts}
    assert sevs == {"ticket"}
    assert all(3.0 <= a["value"] < 14.4 for a in alerts)


# -- anomaly detectors -------------------------------------------------

def test_drift_detector_fires_on_p95_jump_not_on_jitter():
    clk = _FakeClock()
    det = ({"rule": "drift_up", "path": "latency.decode_step_ms.p95",
            "severity": "ticket"},)
    plane, alerts = _plane(clk, detectors=det, anomaly_min_samples=6)
    src = {"latency": {"decode_step_ms": {"p95": 1.0}}}
    plane.register("eng", lambda: json.loads(json.dumps(src)))
    vals = [1.0, 1.05, 0.95, 1.1, 1.0, 1.02, 0.98, 1.04]
    for v in vals:                          # jitter: stays silent
        clk.t += 1.0
        src["latency"]["decode_step_ms"]["p95"] = v
        plane.sample()
    assert alerts == []
    clk.t += 1.0                            # 10x drift: fires
    src["latency"]["decode_step_ms"]["p95"] = 10.0
    plane.sample()
    assert len(alerts) == 1
    a = alerts[0]
    assert a["rule"] == "drift_up"
    assert a["metric"] == "latency.decode_step_ms.p95"
    assert a["value"] == 10.0 and a["threshold"] < 10.0


def test_growth_collapse_and_storm_detectors():
    clk = _FakeClock()
    det = (
        {"rule": "growth", "path": "scheduler.queue_depth",
         "severity": "ticket", "min_samples": 5},
        {"rule": "collapse", "path": "tokens_per_sec",
         "severity": "page", "min_samples": 5},
        {"rule": "storm", "path": "preemptions_per_s",
         "severity": "page", "min_samples": 5},
    )
    plane, alerts = _plane(clk, detectors=det, anomaly_min_samples=5)
    src = {"scheduler": {"queue_depth": 0}, "tokens_per_sec": 100.0,
           "preemptions": 0}
    plane.register("eng", lambda: dict(src, scheduler=dict(
        src["scheduler"])), counters={"preemptions": 0})
    for i in range(8):                      # healthy steady state
        clk.t += 1.0
        plane.sample()
    assert alerts == []
    for i in range(6):                      # queue grows monotonically
        clk.t += 1.0
        src["scheduler"]["queue_depth"] += 2
        plane.sample()
    assert any(a["rule"] == "growth" for a in alerts)
    clk.t += 1.0                            # tokens/s collapses
    src["tokens_per_sec"] = 10.0
    plane.sample()
    assert any(a["rule"] == "collapse" and a["severity"] == "page"
               for a in alerts)
    clk.t += 1.0                            # preemption storm
    src["preemptions"] += 50
    plane.sample()
    assert any(a["rule"] == "storm" for a in alerts)


# -- config coercion ---------------------------------------------------

def test_config_coercion():
    assert TelemetryConfig.coerce(False) is None
    assert TelemetryConfig.coerce(None) is None
    assert isinstance(TelemetryConfig.coerce(True), TelemetryConfig)
    cfg = TelemetryConfig(sample_every=3)
    assert TelemetryConfig.coerce(cfg) is cfg
    with pytest.raises(TypeError, match="TelemetryConfig"):
        TelemetryConfig.coerce(7)


# -- JSONL banking + rotation ------------------------------------------

def test_jsonl_bank_rotation(tmp_path):
    clk = _FakeClock()
    path = str(tmp_path / "tel.jsonl")
    plane, _ = _plane(clk, jsonl_path=path, jsonl_max_bytes=600,
                      jsonl_backups=2)
    plane.register("eng", lambda: {"v": 1, "w": 2.5})
    for _ in range(40):
        clk.t += 1.0
        plane.sample()
    assert os.path.exists(path) and os.path.exists(path + ".1")
    for p in (path, path + ".1"):
        lines = [json.loads(ln) for ln in open(p)]
        assert lines, p                     # every file parses
        assert lines[0]["kind"] == "telemetry_meta"
        assert all(ln["kind"] in ("telemetry_meta", "sample", "alert")
                   for ln in lines)


def test_write_jsonl_one_shot(tmp_path):
    clk = _FakeClock()
    plane, _ = _plane(clk)
    plane.register("eng", lambda: {"v": 3})
    clk.t = 1.0
    plane.sample()
    p = str(tmp_path / "dump.jsonl")
    assert plane.write_jsonl(p) == p
    lines = [json.loads(ln) for ln in open(p)]
    assert lines[0]["kind"] == "telemetry_meta"
    assert lines[0]["schema"] == 1
    assert lines[1]["kind"] == "sample"
    assert lines[1]["values"]["v{component=eng}"] == 3


# -- tools/telemetry_summary.py ----------------------------------------

def _summary_mod():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import telemetry_summary
    finally:
        sys.path.pop(0)
    return telemetry_summary


def test_telemetry_summary_renders_series_and_alerts(tmp_path, capsys):
    ts = _summary_mod()
    clk = _FakeClock()
    plane, _ = _plane(clk, burn_fast_window=2, burn_slow_window=4)
    src = _slo_source()
    plane.register("eng", lambda: json.loads(json.dumps(src)))
    for i in range(6):
        clk.t += 1.0
        src["scheduler"]["slo_seen"] += 10
        src["scheduler"]["slo_attained"] += 10 if i < 3 else 0
        plane.sample()
    p = str(tmp_path / "tel.jsonl")
    plane.write_jsonl(p)
    assert ts.main([p]) == 0
    out = capsys.readouterr().out
    assert "telemetry:" in out
    assert "scheduler.slo_seen" in out
    assert "slo_burn_rate" in out           # the alert log renders
    assert any(ch in out for ch in ts.BLOCKS)   # sparkline present
    assert ts.main([p, "--json"]) == 0
    js = json.loads(capsys.readouterr().out)
    assert js["alerts"] and js["series"]


def test_telemetry_summary_exit_2_on_broken_files(tmp_path, capsys):
    ts = _summary_mod()
    assert ts.main([str(tmp_path / "missing.jsonl")]) == 2
    assert "error:" in capsys.readouterr().err
    trunc = tmp_path / "trunc.jsonl"
    trunc.write_text('{"kind": "sample", "values": {"x"\n')
    assert ts.main([str(trunc)]) == 2
    err = capsys.readouterr().err
    assert "truncated" in err and err.count("error:") == 1
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert ts.main([str(empty)]) == 2
    assert "empty telemetry file" in capsys.readouterr().err


# -- engine integration ------------------------------------------------

def test_engine_stream_parity_exposition_and_clean_silence(
        params, tmp_path, capsys):
    """Acceptance: a 30-request stream with telemetry on produces a
    lint-clean OpenMetrics exposition and a parseable JSONL series
    log, greedy outputs stay bit-identical to the telemetry=False
    engine, and the clean stream raises no alert."""
    def run(telemetry):
        eng = _engine(params, capacity=3, telemetry=telemetry)
        rng = np.random.RandomState(14)
        reqs = []
        pending = [(rng.randint(0, 97, (int(rng.randint(3, 17)),))
                    .astype(np.int32),
                    GenerationConfig(max_new_tokens=int(
                        rng.randint(2, 7)), greedy=True))
                   for _ in range(30)]
        while pending or not eng.idle:
            for _ in range(min(len(pending),
                               1 + int(rng.randint(0, 3)))):
                p, g = pending.pop(0)
                reqs.append(eng.submit(p, g))
            eng.step()
        return eng, [np.asarray(r.output_ids) for r in reqs]

    tel_cfg = TelemetryConfig(sample_every=2, detectors=())
    eng_t, out_t = run(tel_cfg)
    eng_p, out_p = run(False)
    assert all(np.array_equal(a, b) for a, b in zip(out_t, out_p))
    assert eng_p.telemetry is None

    tp = eng_t.telemetry
    snap = eng_t.metrics()["telemetry"]
    assert snap["samples"] >= 10 and snap["series"] > 20
    assert snap["alerts"] == {"page": 0, "ticket": 0}   # clean stream
    text = tp.expose()
    assert lint_exposition(text) == []
    assert "paddle_tpu_tokens_generated_total" in text
    p = str(tmp_path / "tel.jsonl")
    assert tp.write_jsonl(p) == p
    ts = _summary_mod()
    assert ts.main([p]) == 0
    out = capsys.readouterr().out
    assert "alerts: none" in out
    # exposition file writer is atomic and re-readable
    ep = str(tmp_path / "metrics.prom")
    assert tp.write_exposition(ep) == ep
    assert lint_exposition(open(ep).read()) == []


def test_engine_degradation_pages_timeline_and_stall_dump(
        params, tmp_path):
    """Acceptance: injected SLO degradation (deadline-expired burst
    after a clean baseline) raises a burn-rate page that lands an
    ``alert`` timeline event AND a flight-recorder dump naming the
    alert."""
    obs = Observability(stall_dump_path=str(tmp_path / "stall.json"))
    cfg = TelemetryConfig(sample_every=1, detectors=(),
                          burn_fast_window=2, burn_slow_window=4)
    eng = _engine(params, observability=obs, telemetry=cfg)
    rng = np.random.RandomState(3)
    g = GenerationConfig(max_new_tokens=4, greedy=True)
    for _ in range(2):                      # clean baseline
        eng.submit(rng.randint(0, 97, (5,)).astype(np.int32), g)
    eng.drain()
    assert eng.telemetry.snapshot()["alerts"] == {"page": 0,
                                                  "ticket": 0}
    for _ in range(6):                      # degradation: all expire
        eng.submit(rng.randint(0, 97, (5,)).astype(np.int32), g,
                   deadline_s=0.0)
    eng.drain()
    snap = eng.metrics()["telemetry"]
    assert snap["alerts"]["page"] >= 1
    assert snap["rules"].get("slo_burn_rate", 0) >= 1
    evs = [e for e in obs.timeline.events() if e.name == "alert"]
    assert evs and evs[0].meta["rule"] == "slo_burn_rate"
    assert evs[0].meta["severity"] == "page"
    dumps = [p for _, p in obs.stall_dumps if p]
    assert dumps
    report = json.load(open(dumps[0]))
    assert "telemetry alert: slo_burn_rate" in report["reason"]
    alert = report["metrics"]["alert"]
    assert alert["metric"] == "scheduler.slo_burn_rate"
    assert alert["value"] >= 14.4
    assert "queued" in report["scheduler"]  # scheduler snapshot rode


def test_trainer_telemetry_smoke():
    """Trainer wiring: the plane samples train metrics() on the step
    cadence and the frozen schema gains exactly the telemetry key."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed.trainer import (MeshConfig, Trainer,
                                                make_mesh)
    from paddle_tpu.models.llama import (LlamaConfig, init_params,
                                         loss_fn)
    from paddle_tpu.models.llama import param_shardings
    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=32, dtype=jnp.float32,
                      remat=False)
    mesh = make_mesh(MeshConfig(), devices=jax.devices()[:1])
    tr = Trainer(lambda p, t, l: loss_fn(p, t, l, cfg), mesh,
                 param_shardings(mesh, cfg), data_spec=P(), lr=1e-3,
                 telemetry=TelemetryConfig(sample_every=1,
                                           detectors=()))
    assert tr.observability is not None     # telemetry implies obs
    state = tr.init_state(init_params(cfg, jax.random.key(0),
                                      dtype=jnp.float32))
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 97, (2, 8)), jnp.int32)
    labels = jnp.asarray(np.roll(np.asarray(toks), -1, -1))
    for _ in range(3):
        state, _ = tr.step(state, toks, labels)
    m = tr.metrics()
    assert m["telemetry"]["samples"] == 3
    series = {s.path for s in tr.telemetry.series()}
    assert "tokens_per_sec" in series and "steps" in series
    assert "steps_per_s" in series          # counter rate derived
