"""OpTest-style parity tests for the round-2 breadth ops: each op runs
against a numpy reference at fp32 (and bf16 where meaningful) tolerances —
the spirit of reference test/legacy_test/op_test.py:418 check_output.
"""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle

RNG = np.random.RandomState(0)


def t(a):
    return paddle.to_tensor(np.asarray(a))


def n(x):
    return np.asarray(x._value if hasattr(x, "_value") else x)


class TestManipulationBreadth:
    def test_block_diag(self):
        a, b = RNG.randn(2, 3).astype(np.float32), \
            RNG.randn(3, 1).astype(np.float32)
        out = n(paddle.block_diag([t(a), t(b)]))
        ref = np.zeros((5, 4), np.float32)
        ref[:2, :3] = a
        ref[2:, 3:] = b
        np.testing.assert_allclose(out, ref)

    def test_cartesian_prod(self):
        a = np.asarray([1, 2, 3], np.int32)
        b = np.asarray([4, 5], np.int32)
        out = n(paddle.cartesian_prod([t(a), t(b)]))
        ref = np.asarray([[x, y] for x in a for y in b], np.int32)
        np.testing.assert_array_equal(out, ref)

    def test_column_row_stack(self):
        a, b = RNG.randn(4).astype(np.float32), \
            RNG.randn(4).astype(np.float32)
        np.testing.assert_allclose(n(paddle.column_stack([t(a), t(b)])),
                                   np.column_stack([a, b]))
        np.testing.assert_allclose(n(paddle.row_stack([t(a), t(b)])),
                                   np.vstack([a, b]))

    def test_combinations(self):
        a = np.asarray([1, 2, 3, 4], np.int32)
        import itertools
        out = n(paddle.combinations(t(a), 2))
        ref = np.asarray(list(itertools.combinations(a, 2)), np.int32)
        np.testing.assert_array_equal(out, ref)

    def test_diag_embed(self):
        a = RNG.randn(2, 3).astype(np.float32)
        out = n(paddle.diag_embed(t(a)))
        ref = np.stack([np.diag(r) for r in a])
        np.testing.assert_allclose(out, ref)

    def test_diagonal_scatter(self):
        a = RNG.randn(4, 4).astype(np.float32)
        d = RNG.randn(4).astype(np.float32)
        out = n(paddle.diagonal_scatter(t(a), t(d)))
        ref = a.copy()
        np.fill_diagonal(ref, d)
        np.testing.assert_allclose(out, ref)

    def test_select_scatter(self):
        a = RNG.randn(3, 4).astype(np.float32)
        v = RNG.randn(4).astype(np.float32)
        out = n(paddle.select_scatter(t(a), t(v), axis=0, index=1))
        ref = a.copy()
        ref[1] = v
        np.testing.assert_allclose(out, ref)

    def test_slice_scatter(self):
        a = np.zeros((8, 6), np.float32)
        v = np.ones((2, 6), np.float32)
        out = n(paddle.slice_scatter(t(a), t(v), axes=[0], starts=[2],
                                     ends=[6], strides=[2]))
        ref = a.copy()
        ref[2:6:2] = v
        np.testing.assert_allclose(out, ref)

    @pytest.mark.parametrize("fn,axis", [("hsplit", 1), ("vsplit", 0),
                                         ("dsplit", 2)])
    def test_splits(self, fn, axis):
        a = RNG.randn(4, 4, 4).astype(np.float32)
        outs = getattr(paddle, fn)(t(a), 2)
        refs = np.split(a, 2, axis=axis)
        for o, r in zip(outs, refs):
            np.testing.assert_allclose(n(o), r)

    def test_unflatten(self):
        a = RNG.randn(2, 12).astype(np.float32)
        out = n(paddle.unflatten(t(a), 1, [3, -1]))
        np.testing.assert_allclose(out, a.reshape(2, 3, 4))

    def test_unfold(self):
        a = np.arange(9).astype(np.float32)
        out = n(paddle.unfold(t(a), 0, 2, 4))
        ref = np.stack([a[0:2], a[4:6], a[8:9].repeat(2)[:2]])[:2]
        # windows at starts 0, 4 (start 8 would overrun)
        np.testing.assert_allclose(out, np.stack([a[0:2], a[4:6]]))

    def test_unstack(self):
        a = RNG.randn(3, 4).astype(np.float32)
        outs = paddle.unstack(t(a), axis=0)
        assert len(outs) == 3
        for i, o in enumerate(outs):
            np.testing.assert_allclose(n(o), a[i])

    def test_as_strided(self):
        a = np.arange(12).astype(np.float32)
        out = n(paddle.as_strided(t(a), [3, 2], [4, 1]))
        ref = np.lib.stride_tricks.as_strided(a, (3, 2), (16, 4)).copy()
        np.testing.assert_allclose(out, ref)

    def test_matrix_transpose_rank(self):
        a = RNG.randn(2, 3, 4).astype(np.float32)
        np.testing.assert_allclose(n(paddle.matrix_transpose(t(a))),
                                   a.swapaxes(-2, -1))
        assert int(n(paddle.rank(t(a)))) == 3

    def test_masked_scatter(self):
        a = np.zeros(6, np.float32)
        m = np.asarray([1, 0, 1, 1, 0, 0], bool)
        v = np.asarray([7., 8., 9.], np.float32)
        out = n(paddle.masked_scatter(t(a), t(m), t(v)))
        ref = a.copy()
        ref[m] = v
        np.testing.assert_allclose(out, ref)

    def test_index_fill_and_put(self):
        a = RNG.randn(4, 3).astype(np.float32)
        out = n(paddle.index_fill(t(a), t(np.asarray([0, 2])), 0, -1.0))
        ref = a.copy()
        ref[[0, 2]] = -1.0
        np.testing.assert_allclose(out, ref)
        out2 = n(paddle.index_put(t(a), (t(np.asarray([1, 3])),),
                                  t(np.asarray([[9.] * 3, [8.] * 3],
                                               np.float32))))
        ref2 = a.copy()
        ref2[[1, 3]] = [[9.] * 3, [8.] * 3]
        np.testing.assert_allclose(out2, ref2)

    def test_fill_diagonal_(self):
        a = RNG.randn(4, 4).astype(np.float32)
        x = t(a)
        paddle.tensor.fill_diagonal_(x, 5.0)
        ref = a.copy()
        np.fill_diagonal(ref, 5.0)
        np.testing.assert_allclose(n(x), ref)

    def test_tensor_array_to_tensor(self):
        a = RNG.randn(2, 3).astype(np.float32)
        b = RNG.randn(2, 2).astype(np.float32)
        out, sizes = paddle.tensor.tensor_array_to_tensor([t(a), t(b)],
                                                          axis=1)
        np.testing.assert_allclose(n(out), np.concatenate([a, b], axis=1))
        np.testing.assert_array_equal(n(sizes), [3, 2])


class TestMathBreadth:
    def test_gammaln_multigammaln(self):
        from scipy import special  # available via jax's scipy dep? guard
        a = np.asarray([0.5, 1.5, 3.0], np.float32)
        np.testing.assert_allclose(n(paddle.gammaln(t(a))),
                                   special.gammaln(a), rtol=1e-5)
        np.testing.assert_allclose(n(paddle.multigammaln(t(a + 2), 2)),
                                   special.multigammaln(a + 2, 2),
                                   rtol=1e-5)

    def test_small_elementwise(self):
        a = RNG.randn(8).astype(np.float32)
        np.testing.assert_allclose(n(paddle.sinc(t(a))), np.sinc(a),
                                   rtol=1e-5)
        np.testing.assert_array_equal(n(paddle.signbit(t(a))),
                                      np.signbit(a))
        np.testing.assert_allclose(n(paddle.negative(t(a))), -a)
        np.testing.assert_allclose(n(paddle.positive(t(a))), a)
        p = np.clip(np.abs(a), 0.01, 0.99)
        np.testing.assert_allclose(n(paddle.logit(t(p))),
                                   np.log(p / (1 - p)), rtol=1e-4)

    def test_isin(self):
        a = np.asarray([1, 2, 3, 4], np.int32)
        tst = np.asarray([2, 4], np.int32)
        np.testing.assert_array_equal(n(paddle.isin(t(a), t(tst))),
                                      np.isin(a, tst))

    def test_add_n(self):
        xs = [RNG.randn(3, 3).astype(np.float32) for _ in range(3)]
        np.testing.assert_allclose(n(paddle.add_n([t(x) for x in xs])),
                                   sum(xs), rtol=1e-6)

    def test_trapezoid(self):
        y = RNG.rand(16).astype(np.float32)
        x = np.sort(RNG.rand(16).astype(np.float32))
        np.testing.assert_allclose(n(paddle.trapezoid(t(y), t(x))),
                                   np.trapezoid(y, x), rtol=1e-5)
        out = n(paddle.cumulative_trapezoid(t(y), t(x)))
        ref = np.cumsum((y[:-1] + y[1:]) * 0.5 * np.diff(x))
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_vecdot_mm_ldexp(self):
        a = RNG.randn(3, 4).astype(np.float32)
        b = RNG.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(n(paddle.vecdot(t(a), t(b))),
                                   (a * b).sum(-1), rtol=1e-5)
        m = RNG.randn(4, 2).astype(np.float32)
        np.testing.assert_allclose(n(paddle.mm(t(a), t(m))), a @ m,
                                   rtol=1e-5)
        e = np.asarray([1, 2, 3], np.int32)
        np.testing.assert_allclose(
            n(paddle.tensor.ldexp(t(np.asarray([1., 1., 1.], np.float32)),
                                  t(e))), np.ldexp([1., 1., 1.], e))

    def test_histogram_bin_edges(self):
        a = RNG.rand(32).astype(np.float32)
        out = n(paddle.histogram_bin_edges(t(a), bins=8))
        ref = np.histogram_bin_edges(a, bins=8)
        np.testing.assert_allclose(out, ref, rtol=1e-5)


class TestLinalgBreadth:
    def test_inverse_cond(self):
        a = RNG.randn(4, 4).astype(np.float32) + 4 * np.eye(4, dtype=np.float32)
        np.testing.assert_allclose(n(paddle.inverse(t(a))),
                                   np.linalg.inv(a), rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(float(n(paddle.tensor.cond(t(a)))),
                                   np.linalg.cond(a), rtol=1e-3)

    def test_cholesky_inverse(self):
        a = RNG.randn(4, 4).astype(np.float32)
        spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        L = np.linalg.cholesky(spd)
        out = n(paddle.tensor.cholesky_inverse(t(L)))
        np.testing.assert_allclose(out, np.linalg.inv(spd), rtol=1e-3,
                                   atol=1e-4)

    def test_svd_lowrank(self):
        # own generator: the shared module RNG's state depends on which
        # tests ran before on this xdist worker, and reconstruction
        # tolerance is draw-dependent
        rng = np.random.RandomState(7)
        a = (rng.randn(8, 3) @ rng.randn(3, 6)).astype(np.float32)
        u, s, v = paddle.tensor.svd_lowrank(t(a), q=3)
        rec = n(u) * n(s)[None, :] @ n(v).T
        np.testing.assert_allclose(rec, a, atol=1e-3)


class TestInplaceAndTypes:
    def test_generated_inplace(self):
        a = RNG.randn(4).astype(np.float32)
        x = t(a)
        r = x.tanh_()
        assert r is x
        np.testing.assert_allclose(n(x), np.tanh(a), rtol=1e-6)
        x2 = t(a)
        x2.add_(t(np.ones(4, np.float32)))
        np.testing.assert_allclose(n(x2), a + 1)

    def test_zero_fill_set(self):
        x = t(RNG.randn(3).astype(np.float32))
        x.zero_()
        np.testing.assert_allclose(n(x), np.zeros(3))
        x.fill_(2.5)
        np.testing.assert_allclose(n(x), np.full(3, 2.5))
        x.set_(t(np.arange(6, dtype=np.float32)), shape=(2, 3))
        assert n(x).shape == (2, 3)

    def test_type_predicates(self):
        assert paddle.is_floating_point(t(np.zeros(2, np.float32)))
        assert not paddle.is_floating_point(t(np.zeros(2, np.int32)))
        assert paddle.is_integer(t(np.zeros(2, np.int32)))
        assert not paddle.is_complex(t(np.zeros(2, np.float32)))

    @pytest.mark.slow
    def test_random_breadth(self):
        g = paddle.tensor.gaussian([1000], mean=2.0, std=0.5)
        assert abs(float(n(g).mean()) - 2.0) < 0.1
        sg = paddle.tensor.standard_gamma(t(np.full(1000, 3.0, np.float32)))
        assert abs(float(n(sg).mean()) - 3.0) < 0.3
        ln = paddle.tensor.log_normal(mean=0.0, std=0.25, shape=[1000])
        assert abs(float(np.log(n(ln)).mean())) < 0.1
        x = t(np.zeros(1000, np.float32))
        x.gaussian_(mean=1.0, std=0.1)
        assert abs(float(n(x).mean()) - 1.0) < 0.05


class TestRound3LongTail:
    """Round-3 long-tail additions (reference: tensor/math.py reduce_as,
    tensor/search.py top_p_sampling, nn/functional/distance.py pdist,
    framework/dtype.py finfo/iinfo, generated inplace op_ siblings)."""

    def test_inplace_trig_pack(self):
        rng = np.random.RandomState(3)
        for name, ref in [("sqrt_", np.sqrt), ("exp_", np.exp),
                          ("sin_", np.sin), ("cos_", np.cos),
                          ("floor_", np.floor), ("ceil_", np.ceil),
                          ("abs_", np.abs), ("tan_", np.tan),
                          ("sigmoid_", lambda v: 1 / (1 + np.exp(-v))),
                          ("rsqrt_", lambda v: 1 / np.sqrt(v)),
                          ("reciprocal_", lambda v: 1 / v),
                          ("square_", np.square)]:
            a = np.abs(rng.randn(5).astype(np.float32)) + 0.5
            x = t(a.copy())
            r = getattr(x, name)()
            assert r is x, name
            np.testing.assert_allclose(n(x), ref(a), rtol=1e-5,
                                       err_msg=name)

    def test_reduce_as_matches_broadcast_transpose(self):
        x = t(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
        tgt = t(np.zeros((3, 1), np.float32))
        out = paddle.reduce_as(x, tgt)
        np.testing.assert_allclose(
            n(out), np.arange(24).reshape(2, 3, 4).sum((0, 2),
                                                       keepdims=True)[0])
        # int32 promotes to int64 (reference dtype rule)
        xi = t(np.ones((2, 3), np.int32))
        got = paddle.reduce_as(xi, t(np.zeros((3,), np.int32)))
        assert "int64" in str(got.dtype)

    def test_top_p_sampling_nucleus(self):
        paddle.seed(0)
        probs = t(np.array([[0.5, 0.3, 0.15, 0.05]] * 64, np.float32))
        val, ids = paddle.top_p_sampling(
            probs, t(np.full((64,), 0.75, np.float32)))
        i = n(ids).ravel()
        assert set(i.tolist()) <= {0, 1}          # nucleus = {0.5, 0.3}
        assert len(set(i.tolist())) == 2          # actually samples both
        np.testing.assert_allclose(
            n(val).ravel(), np.where(i == 0, 0.5, 0.3), rtol=1e-6)
        # k cap: top-1 only
        _, ids1 = paddle.top_p_sampling(
            probs, t(np.full((64,), 0.99, np.float32)), k=1)
        assert set(n(ids1).ravel().tolist()) == {0}

    def test_pdist_matches_scipy_form(self):
        rng = np.random.RandomState(1)
        a = rng.randn(5, 3).astype(np.float32)
        got = n(paddle.pdist(t(a)))
        want = []
        for i in range(5):
            for j in range(i + 1, 5):
                want.append(np.linalg.norm(a[i] - a[j]))
        np.testing.assert_allclose(got, want, rtol=1e-5)
        inf_d = n(paddle.pdist(t(a), p=float("inf")))
        want_inf = [np.abs(a[i] - a[j]).max()
                    for i in range(5) for j in range(i + 1, 5)]
        np.testing.assert_allclose(inf_d, want_inf, rtol=1e-5)

    def test_finfo_iinfo_constants(self):
        assert paddle.finfo("float32").eps == np.finfo(np.float32).eps
        assert paddle.finfo("bfloat16").bits == 16
        assert paddle.iinfo("int16").max == 32767
        assert paddle.pi == np.pi and paddle.inf == np.inf
        assert paddle.newaxis is None and np.isnan(paddle.nan)

    def test_dlpack_roundtrip_and_torch_interop(self):
        a = np.arange(6, dtype=np.float32)
        back = paddle.from_dlpack(paddle.to_dlpack(t(a)))
        np.testing.assert_allclose(n(back), a)
        import torch
        np.testing.assert_allclose(
            n(paddle.from_dlpack(torch.arange(4, dtype=torch.float32))),
            [0, 1, 2, 3])

    def test_resize_reverse_create(self):
        x = t(np.arange(6, dtype=np.float32))
        x.resize_([2, 4])
        np.testing.assert_allclose(n(x), [[0, 1, 2, 3], [4, 5, 0, 0]])
        x.resize_([3])
        np.testing.assert_allclose(n(x), [0, 1, 2])
        np.testing.assert_allclose(
            n(paddle.reverse(t(np.array([1., 2.], np.float32)), 0)),
            [2, 1])
        p = paddle.create_parameter([3, 3], "float32")
        assert not p.stop_gradient and list(p.shape) == [3, 3]
        ct = paddle.create_tensor("int32")
        assert "int32" in str(ct.dtype)

    def test_rng_state_shape_guard_misc(self):
        st = paddle.get_cuda_rng_state()
        paddle.set_cuda_rng_state(st)
        assert paddle.check_shape([2, -1, None]) == [2, -1, None]
        with pytest.raises(ValueError):
            paddle.check_shape([2, 0])
        paddle.disable_signal_handler()
        assert paddle.broadcast_shape([2, 1, 4], [3, 1]) == [2, 3, 4]

    def test_flops_counts_linear_and_conv(self):
        import paddle_tpu.nn as nn
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        assert paddle.flops(net, input_size=[1, 8]) == \
            2 * 8 * 16 + 16 + 2 * 16 * 2
        conv = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1))
        f = paddle.flops(conv, input_size=[1, 3, 8, 8])
        assert f == 2 * (8 * 8 * 8) * (3 * 3 * 3)

    def test_stft_istft_methods(self):
        sig = np.random.RandomState(0).randn(256).astype(np.float32)
        S = t(sig).stft(n_fft=64, hop_length=16)
        back = S.istft(n_fft=64, hop_length=16, length=256)
        err = np.abs(n(back) - sig)[32:-32].max()
        assert err < 1e-3


class TestRound3Extras:
    """gather_tree, fractional pooling, ASGD/Rprop optimizers
    (reference: gather_tree_kernel.cc, funcs/pooling.h fractional index
    math, optimizer/asgd.py, cpu/rprop_kernel.cc)."""

    def test_gather_tree_matches_reference_loop(self):
        from paddle_tpu.tensor.manipulation import gather_tree
        rng = np.random.RandomState(0)
        T, B, W = 5, 3, 4
        ids = rng.randint(0, 9, (T, B, W)).astype(np.int64)
        par = rng.randint(0, W, (T, B, W)).astype(np.int64)
        out = n(gather_tree(t(ids), t(par)))
        ref = np.zeros_like(ids)
        for b in range(B):
            for w in range(W):
                ref[T - 1, b, w] = ids[T - 1, b, w]
                parent = par[T - 1, b, w]
                for s in range(T - 2, -1, -1):
                    ref[s, b, w] = ids[s, b, parent]
                    parent = par[s, b, parent]
        np.testing.assert_array_equal(out, ref)

    def test_fractional_max_pool(self):
        import paddle_tpu.nn.functional as F
        x = RNG.randn(2, 3, 9, 9).astype(np.float32)
        o1 = n(F.fractional_max_pool2d(t(x), 4, random_u=0.3))
        o2 = n(F.fractional_max_pool2d(t(x), 4, random_u=0.3))
        np.testing.assert_array_equal(o1, o2)     # u fixes the grid
        assert o1.shape == (2, 3, 4, 4)
        ov, om = F.fractional_max_pool2d(t(x), 4, random_u=0.3,
                                         return_mask=True)
        ov, om = n(ov), n(om)
        flat = x.reshape(2, 3, 81)
        np.testing.assert_allclose(
            np.take_along_axis(flat, om.reshape(2, 3, -1),
                               -1).reshape(ov.shape), ov)
        # kernel_size form uses u directly
        ok = n(F.fractional_max_pool2d(t(x), 4, kernel_size=2,
                                       random_u=0.7))
        assert ok.shape == (2, 3, 4, 4)

    def test_asgd_batchnum1_is_sgd_with_decay(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        lin = nn.Linear(4, 2)
        w0 = n(lin.weight).copy()
        opt = paddle.optimizer.ASGD(0.1, parameters=lin.parameters())
        x = t(np.ones((2, 4), np.float32))
        out = lin(x)
        out.sum().backward()
        g = n(lin.weight.grad)
        opt.step()
        np.testing.assert_allclose(n(lin.weight), w0 - 0.1 * g, atol=1e-6)

    def test_rprop_sign_adaptation(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        lin = nn.Linear(2, 1, bias_attr=False)
        opt = paddle.optimizer.Rprop(0.01, parameters=lin.parameters(),
                                     etas=(0.5, 1.2))
        x = t(np.ones((1, 2), np.float32))
        w_hist = [n(lin.weight).copy()]
        for _ in range(3):
            lin(x).sum().backward()   # constant positive gradient
            opt.step()
            opt.clear_grad()
            w_hist.append(n(lin.weight).copy())
        d1 = np.abs(w_hist[1] - w_hist[0])
        d2 = np.abs(w_hist[2] - w_hist[1])
        d3 = np.abs(w_hist[3] - w_hist[2])
        np.testing.assert_allclose(d1, 0.01, atol=1e-6)  # initial step
        np.testing.assert_allclose(d2, 0.012, atol=1e-6)  # * eta+
        np.testing.assert_allclose(d3, 0.0144, atol=1e-6)
        # loss decreases on a quadratic with sign flips handled
        paddle.seed(1)
        lin2 = nn.Linear(4, 1)
        opt2 = paddle.optimizer.Rprop(0.01, parameters=lin2.parameters())
        xv = t(RNG.randn(16, 4).astype(np.float32))
        yv = t(RNG.randn(16, 1).astype(np.float32))
        import paddle_tpu.nn.functional as F
        losses = []
        for _ in range(20):
            loss = F.mse_loss(lin2(xv), yv)
            loss.backward()
            opt2.step()
            opt2.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestRound4Parity:
    def test_api_parity_registries_diff_clean(self):
        """Round-3 verdict Next #9: the measured diff against the
        reference's tensor_method_func registry and paddle.__all__ must
        stay closed (tools/check_api_parity.py is the living list)."""
        import subprocess
        import sys
        import os
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if not os.path.isdir("/root/reference/python/paddle"):
            pytest.skip("reference checkout not available")
        p = subprocess.run(
            [sys.executable, os.path.join(root, "tools",
                                          "check_api_parity.py")],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert p.returncode == 0, p.stdout + p.stderr

    def test_lazy_guard_defers_initializer(self):
        import paddle_tpu.nn as nn
        from paddle_tpu import LazyGuard
        paddle.seed(7)
        with LazyGuard():
            lin = nn.Linear(16, 16)
        # deferred: the placeholder is zeros, spec is stashed
        assert float(np.abs(lin.weight.numpy()).sum()) == 0.0
        assert lin.weight._lazy_spec is not None
        lin.weight.initialize()
        lin.bias.initialize()
        assert lin.weight._lazy_spec is None
        assert float(np.abs(lin.weight.numpy()).sum()) > 0  # materialized
        # eager construction unaffected
        lin2 = nn.Linear(4, 4)
        assert getattr(lin2.weight, "_lazy_spec", None) is None
        assert float(np.abs(lin2.weight.numpy()).sum()) > 0

    def test_top_level_shape_tolist_dtype_places(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        np.testing.assert_array_equal(paddle.shape(x).numpy(), [2, 3])
        assert paddle.tolist(x) == [[0.0, 1.0, 2.0], [3.0, 4.0, 5.0]]
        assert paddle.dtype("float32") == np.float32
        p = paddle.CUDAPinnedPlace()
        assert "pinned" in repr(p)
        assert paddle.DataParallel is not None
