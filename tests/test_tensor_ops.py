"""Op parity vs numpy (OpTest analog; reference test strategy SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_output, check_grad


class TestCreation:
    def test_to_tensor(self):
        t = paddle.to_tensor([1, 2, 3])
        assert t.dtype == np.int64
        t = paddle.to_tensor([1.0, 2.0])
        assert t.dtype == np.float32

    def test_zeros_ones_full(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2, 3]).numpy().sum() == 6
        assert (paddle.full([2], 7).numpy() == 7).all()

    def test_arange_linspace_eye(self):
        np.testing.assert_array_equal(paddle.arange(5).numpy(),
                                      np.arange(5))
        np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(),
                                   np.linspace(0, 1, 5), rtol=1e-6)
        np.testing.assert_array_equal(paddle.eye(3).numpy(), np.eye(3,
                                      dtype=np.float32))

    def test_like_family(self):
        x = paddle.randn([3, 4])
        assert paddle.zeros_like(x).shape == [3, 4]
        assert (paddle.full_like(x, 2.5).numpy() == 2.5).all()

    def test_tril_triu_diag(self):
        a = np.arange(16, dtype=np.float32).reshape(4, 4)
        check_output(paddle.tril, np.tril, [a])
        check_output(paddle.triu, np.triu, [a])
        check_output(paddle.diag, np.diag, [np.arange(4., dtype=np.float32)])


class TestMath:
    @pytest.mark.parametrize("name,np_fn", [
        ("exp", np.exp), ("log", lambda x: np.log(np.abs(x) + 1)),
        ("sqrt", lambda x: np.sqrt(np.abs(x))), ("abs", np.abs),
        ("sin", np.sin), ("cos", np.cos), ("tanh", np.tanh),
        ("floor", np.floor), ("ceil", np.ceil), ("round", np.round),
        ("sign", np.sign), ("square", np.square),
    ])
    def test_unary(self, name, np_fn):
        # XLA's vectorized transcendentals differ from libm at ~1e-4 rel
        tol = dict(atol=5e-4, rtol=5e-4)
        x = np.random.randn(3, 4).astype(np.float32)
        if name == "log":
            arg = np.abs(x) + 1
            check_output(getattr(paddle, name), np.log, [arg], **tol)
        elif name == "sqrt":
            check_output(getattr(paddle, name), np.sqrt, [np.abs(x)], **tol)
        else:
            check_output(getattr(paddle, name), np_fn, [x], **tol)

    @pytest.mark.parametrize("name,np_fn", [
        ("add", np.add), ("subtract", np.subtract),
        ("multiply", np.multiply), ("divide", np.divide),
        ("maximum", np.maximum), ("minimum", np.minimum),
        ("atan2", np.arctan2),
    ])
    def test_binary(self, name, np_fn):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(3, 4).astype(np.float32) + 2.0
        check_output(getattr(paddle, name), np_fn, [a, b])

    def test_reductions(self):
        x = np.random.randn(3, 4, 5).astype(np.float32)
        check_output(paddle.sum, np.sum, [x])
        check_output(lambda t: paddle.sum(t, axis=1),
                     lambda a: a.sum(axis=1), [x])
        check_output(lambda t: paddle.mean(t, axis=[0, 2], keepdim=True),
                     lambda a: a.mean(axis=(0, 2), keepdims=True), [x])
        check_output(paddle.max, np.max, [x])
        check_output(lambda t: paddle.prod(t, axis=-1),
                     lambda a: a.prod(axis=-1), [x])

    def test_cumsum_cumprod(self):
        x = np.random.rand(3, 4).astype(np.float32)
        check_output(lambda t: paddle.cumsum(t, axis=1),
                     lambda a: np.cumsum(a, axis=1), [x])
        check_output(lambda t: paddle.cumprod(t, dim=0),
                     lambda a: np.cumprod(a, axis=0), [x])

    def test_clip_lerp(self):
        x = np.random.randn(4, 4).astype(np.float32)
        check_output(lambda t: paddle.clip(t, -0.5, 0.5),
                     lambda a: np.clip(a, -0.5, 0.5), [x])

    def test_logsumexp(self):
        from scipy.special import logsumexp as np_lse
        x = np.random.randn(3, 5).astype(np.float32)
        check_output(lambda t: paddle.logsumexp(t, axis=1),
                     lambda a: np_lse(a, axis=1), [x])

    def test_operators(self):
        a = paddle.to_tensor([1.0, 2.0])
        b = paddle.to_tensor([3.0, 4.0])
        np.testing.assert_allclose((a + b).numpy(), [4, 6])
        np.testing.assert_allclose((a - 1).numpy(), [0, 1])
        np.testing.assert_allclose((2 * a).numpy(), [2, 4])
        np.testing.assert_allclose((a ** 2).numpy(), [1, 4])
        np.testing.assert_allclose((-a).numpy(), [-1, -2])
        assert (a < b).numpy().all()

    def test_allclose_isnan(self):
        x = paddle.to_tensor([1.0, np.nan, np.inf])
        np.testing.assert_array_equal(paddle.isnan(x).numpy(),
                                      [False, True, False])
        np.testing.assert_array_equal(paddle.isinf(x).numpy(),
                                      [False, False, True])


class TestLinalg:
    def test_matmul(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(4, 5).astype(np.float32)
        check_output(paddle.matmul, np.matmul, [a, b])

    def test_matmul_transpose(self):
        a = np.random.randn(4, 3).astype(np.float32)
        b = np.random.randn(4, 5).astype(np.float32)
        check_output(lambda x, y: paddle.matmul(x, y, transpose_x=True),
                     lambda x, y: x.T @ y, [a, b])

    def test_batched_matmul(self):
        a = np.random.randn(2, 3, 4).astype(np.float32)
        b = np.random.randn(2, 4, 5).astype(np.float32)
        check_output(paddle.bmm, np.matmul, [a, b])

    def test_norm_det_inv(self):
        a = np.random.randn(3, 3).astype(np.float32)
        a = a @ a.T + 3 * np.eye(3, dtype=np.float32)
        check_output(paddle.linalg.det, np.linalg.det, [a], atol=1e-4)
        check_output(paddle.linalg.inv, np.linalg.inv, [a], atol=1e-4)
        check_output(lambda t: paddle.norm(t),
                     lambda x: np.linalg.norm(x), [a], atol=1e-4)

    def test_einsum(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(4, 5).astype(np.float32)
        out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a),
                            paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5,
                                   atol=1e-5)

    def test_svd_qr(self):
        a = np.random.randn(4, 3).astype(np.float32)
        u, s, vh = paddle.linalg.svd(paddle.to_tensor(a))
        rec = u.numpy() @ np.diag(s.numpy()) @ vh.numpy()
        np.testing.assert_allclose(rec, a, atol=1e-4)
        q, r = paddle.linalg.qr(paddle.to_tensor(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-4)

    def test_solve(self):
        a = np.random.randn(3, 3).astype(np.float32) + 3 * np.eye(
            3, dtype=np.float32)
        b = np.random.randn(3, 2).astype(np.float32)
        check_output(paddle.linalg.solve, np.linalg.solve, [a, b], atol=1e-4)


class TestManipulation:
    def test_reshape_transpose(self):
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        check_output(lambda t: paddle.reshape(t, [4, 6]),
                     lambda a: a.reshape(4, 6), [x])
        check_output(lambda t: paddle.transpose(t, [2, 0, 1]),
                     lambda a: a.transpose(2, 0, 1), [x])

    def test_concat_stack_split(self):
        a = np.random.randn(2, 3).astype(np.float32)
        b = np.random.randn(2, 3).astype(np.float32)
        out = paddle.concat([paddle.to_tensor(a), paddle.to_tensor(b)], axis=0)
        np.testing.assert_allclose(out.numpy(), np.concatenate([a, b]))
        out = paddle.stack([paddle.to_tensor(a), paddle.to_tensor(b)], axis=1)
        np.testing.assert_allclose(out.numpy(), np.stack([a, b], axis=1))
        parts = paddle.split(paddle.to_tensor(a), 3, axis=1)
        assert len(parts) == 3 and parts[0].shape == [2, 1]
        parts = paddle.split(paddle.to_tensor(a), [1, -1], axis=1)
        assert parts[1].shape == [2, 2]

    def test_squeeze_unsqueeze_flatten(self):
        x = np.random.randn(2, 1, 3).astype(np.float32)
        assert paddle.squeeze(paddle.to_tensor(x), axis=1).shape == [2, 3]
        assert paddle.unsqueeze(paddle.to_tensor(x), [0]).shape == [1, 2, 1, 3]
        assert paddle.flatten(paddle.to_tensor(x)).shape == [6]

    def test_gather_scatter(self):
        x = np.arange(12, dtype=np.float32).reshape(4, 3)
        idx = np.array([0, 2])
        out = paddle.gather(paddle.to_tensor(x), paddle.to_tensor(idx))
        np.testing.assert_allclose(out.numpy(), x[idx])
        upd = np.ones((2, 3), np.float32)
        out = paddle.scatter(paddle.to_tensor(x), paddle.to_tensor(idx),
                             paddle.to_tensor(upd))
        want = x.copy()
        want[idx] = 1.0
        np.testing.assert_allclose(out.numpy(), want)

    def test_where_masked(self):
        x = np.random.randn(3, 4).astype(np.float32)
        y = np.zeros((3, 4), np.float32)
        cond = x > 0
        out = paddle.where(paddle.to_tensor(cond), paddle.to_tensor(x),
                           paddle.to_tensor(y))
        np.testing.assert_allclose(out.numpy(), np.where(cond, x, y))
        ms = paddle.masked_select(paddle.to_tensor(x),
                                  paddle.to_tensor(cond))
        np.testing.assert_allclose(ms.numpy(), x[cond])

    def test_indexing(self):
        x = paddle.to_tensor(np.arange(24, dtype=np.float32
                                       ).reshape(4, 6))
        np.testing.assert_allclose(x[1].numpy(), np.arange(6, 12))
        np.testing.assert_allclose(x[:, 2].numpy(), [2, 8, 14, 20])
        np.testing.assert_allclose(x[1:3, ::2].shape, [2, 3])
        x[0] = 0.0
        assert x.numpy()[0].sum() == 0

    def test_pad_tile_flip(self):
        x = np.random.randn(2, 3).astype(np.float32)
        out = paddle.tile(paddle.to_tensor(x), [2, 1])
        np.testing.assert_allclose(out.numpy(), np.tile(x, (2, 1)))
        out = paddle.flip(paddle.to_tensor(x), [0])
        np.testing.assert_allclose(out.numpy(), x[::-1])

    def test_unique(self):
        x = np.array([3, 1, 2, 1, 3])
        out = paddle.unique(paddle.to_tensor(x))
        np.testing.assert_array_equal(out.numpy(), [1, 2, 3])


class TestSearch:
    def test_argmax_argsort(self):
        x = np.random.randn(3, 5).astype(np.float32)
        check_output(lambda t: paddle.argmax(t, axis=1),
                     lambda a: np.argmax(a, axis=1), [x])
        check_output(lambda t: paddle.argsort(t, axis=1),
                     lambda a: np.argsort(a, axis=1, kind="stable"), [x])
        check_output(lambda t: paddle.sort(t, axis=1),
                     lambda a: np.sort(a, axis=1), [x])

    def test_topk(self):
        x = np.random.randn(4, 10).astype(np.float32)
        vals, idx = paddle.topk(paddle.to_tensor(x), k=3, axis=1)
        want = np.sort(x, axis=1)[:, ::-1][:, :3]
        np.testing.assert_allclose(vals.numpy(), want, rtol=1e-6)

    def test_nonzero_searchsorted(self):
        x = np.array([0.0, 1.5, 0.0, 2.0], np.float32)
        idx = paddle.nonzero(paddle.to_tensor(x))
        np.testing.assert_array_equal(idx.numpy().ravel(), [1, 3])
        s = np.array([1.0, 3.0, 5.0], np.float32)
        v = np.array([2.0, 4.0], np.float32)
        out = paddle.searchsorted(paddle.to_tensor(s), paddle.to_tensor(v))
        np.testing.assert_array_equal(out.numpy(), [1, 2])


class TestStat:
    def test_std_var_median(self):
        x = np.random.randn(4, 5).astype(np.float32)
        check_output(lambda t: paddle.std(t, axis=1),
                     lambda a: a.std(axis=1, ddof=1), [x])
        check_output(lambda t: paddle.var(t, axis=0, unbiased=False),
                     lambda a: a.var(axis=0), [x])
        check_output(paddle.median, np.median, [x])


class TestRandom:
    def test_shapes_and_determinism(self):
        paddle.seed(7)
        a = paddle.randn([3, 4])
        paddle.seed(7)
        b = paddle.randn([3, 4])
        np.testing.assert_allclose(a.numpy(), b.numpy())
        assert paddle.rand([2, 2]).shape == [2, 2]
        r = paddle.randint(0, 10, [100])
        assert r.numpy().min() >= 0 and r.numpy().max() < 10
        p = paddle.randperm(10).numpy()
        np.testing.assert_array_equal(np.sort(p), np.arange(10))

    def test_bernoulli_multinomial(self):
        probs = paddle.full([1000], 0.3)
        draws = paddle.bernoulli(probs)
        assert 0.2 < float(draws.numpy().mean()) < 0.4
        m = paddle.multinomial(paddle.to_tensor([0.1, 0.0, 0.9]), 50,
                               replacement=True)
        vals = set(m.numpy().tolist())
        assert 1 not in vals


class TestDtype:
    def test_cast(self):
        x = paddle.to_tensor([1.7, 2.3])
        assert x.astype("int32").dtype == np.int32
        assert x.astype(paddle.bfloat16).dtype == paddle.bfloat16

    def test_promotion(self):
        a = paddle.to_tensor([1, 2])  # int64
        b = paddle.to_tensor([0.5, 0.5])
        assert (a + b).dtype == np.float32


# -- device Stream/Event API (reference: python/paddle/device Stream/Event)
class TestStreamEvent:
    def test_event_record_query_sync(self):
        from paddle_tpu import device as D
        import jax.numpy as jnp

        e1 = D.Event(enable_timing=True)
        e1.record()
        _ = jnp.ones((256, 256)) @ jnp.ones((256, 256))
        e2 = D.Event(enable_timing=True)
        e2.record()
        e2.synchronize()
        assert e2.query()
        assert e1.elapsed_time(e2) >= 0.0

    def test_stream_guard_swaps_current(self):
        from paddle_tpu import device as D

        base = D.current_stream()
        s2 = D.Stream()
        with D.stream_guard(s2):
            assert D.current_stream() is s2
        assert D.current_stream() is base
        s2.wait_stream(base)
        base.synchronize()


class TestTensorArray:
    """reference: python/paddle/tensor/array.py."""

    def test_write_read_length(self):
        arr = paddle.tensor.create_array()
        arr = paddle.tensor.array_write(paddle.to_tensor([1.0, 2.0]),
                                        paddle.to_tensor(0), arr)
        arr = paddle.tensor.array_write(paddle.to_tensor([3.0, 4.0]), 1,
                                        arr)
        assert int(paddle.tensor.array_length(arr)) == 2
        np.testing.assert_allclose(
            np.asarray(paddle.tensor.array_read(arr, 1).numpy()), [3, 4])
        # overwrite
        arr = paddle.tensor.array_write(paddle.to_tensor([9.0, 9.0]), 0,
                                        arr)
        np.testing.assert_allclose(
            np.asarray(paddle.tensor.array_read(arr, 0).numpy()), [9, 9])
        with pytest.raises(IndexError):
            paddle.tensor.array_write(paddle.to_tensor([0.0]), 5, arr)

    def test_stack_roundtrip(self):
        from paddle_tpu.tensor.manipulation import tensor_array_to_tensor
        arr = paddle.tensor.create_array(
            initialized_list=[np.ones(3, np.float32) * i for i in range(4)])
        out, _ = tensor_array_to_tensor(arr, axis=0, use_stack=True)
        assert np.asarray(out.numpy()).shape == (4, 3)
