"""FasterTokenizer (native C++ + python fallback), StringTensor, and the
fp8 path (reference: faster_tokenizer_op.cc, phi/core/string_tensor.h,
phi/kernels/fusion/fp8_gemm)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.text import FasterTokenizer, StringTensor, strings

CJK_NI = "你"   # 你
CJK_HAO = "好"  # 好
VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "hello", "world", "un",
         "##aff", "##able", "the", "quick", "brown", "fox", ",", "!",
         CJK_NI, CJK_HAO]


class TestFasterTokenizer:
    def _tok(self, **kw):
        return FasterTokenizer(VOCAB, **kw)

    def test_native_backend_loads(self):
        if os.environ.get("PADDLE_TPU_DISABLE_NATIVE") == "1":
            pytest.skip("native disabled")
        assert self._tok().backend == "native"

    def test_wordpiece_and_case(self):
        tok = self._tok()
        v = {t: i for i, t in enumerate(VOCAB)}
        assert tok.tokenize("Hello world") == [v["hello"], v["world"]]
        assert tok.tokenize("unaffable") == [v["un"], v["##aff"],
                                             v["##able"]]
        assert tok.tokenize("xyzzy") == [v["[UNK]"]]

    def test_punct_and_cjk_split(self):
        tok = self._tok()
        v = {t: i for i, t in enumerate(VOCAB)}
        assert tok.tokenize("hello,world!") == [v["hello"], v[","],
                                                v["world"], v["!"]]
        assert tok.tokenize(CJK_NI + CJK_HAO) == [v[CJK_NI], v[CJK_HAO]]

    def test_encode_single_and_pair(self):
        tok = self._tok(max_seq_len=10)
        v = {t: i for i, t in enumerate(VOCAB)}
        ids, segs = tok("hello world")
        assert ids.shape == [1, 10] and segs.shape == [1, 10]
        row = np.asarray(ids.numpy())[0]
        np.testing.assert_array_equal(
            row[:4], [v["[CLS]"], v["hello"], v["world"], v["[SEP]"]])
        assert (row[4:] == v["[PAD]"]).all()
        ids, segs = tok(["hello"], text_pair=["world"])
        row, seg = np.asarray(ids.numpy())[0], np.asarray(segs.numpy())[0]
        np.testing.assert_array_equal(
            row[:5], [v["[CLS]"], v["hello"], v["[SEP]"], v["world"],
                      v["[SEP]"]])
        np.testing.assert_array_equal(seg[:5], [0, 0, 0, 1, 1])

    def test_truncation_longest_first(self):
        tok = self._tok(max_seq_len=6)
        ids, _ = tok(["the quick brown fox"], text_pair=["hello world"])
        row = np.asarray(ids.numpy())[0]
        assert len(row) == 6
        v = {t: i for i, t in enumerate(VOCAB)}
        assert row[0] == v["[CLS]"] and (row == v["[SEP]"]).sum() == 2

    def test_python_fallback_matches_native(self):
        """The fallback mirrors the native char classes exactly — same
        ids for Latin-1/Greek/Cyrillic, byte-limit words, punct."""
        ext_vocab = VOCAB + ["ärger", "αβ", "да",
                             "¡"]
        native = FasterTokenizer(ext_vocab, max_seq_len=12)
        if native.backend != "native":
            pytest.skip("native unavailable; nothing to compare")
        py = FasterTokenizer(ext_vocab, max_seq_len=12)
        py._h = None   # force the python path
        py.backend = "python"
        long_word = "α" * 60   # 120 utf-8 bytes: over the limit
        for text, pair in [("Hello, world!", None),
                           ("unaffable fox", "the quick brown fox"),
                           (CJK_NI + CJK_HAO + " world", None),
                           ("Ärger ΑΒ ДА", None),
                           ("¡hola!", None),
                           (long_word, None)]:
            a = [np.asarray(t.numpy()) for t in native(text, pair)]
            b = [np.asarray(t.numpy()) for t in py(text, pair)]
            np.testing.assert_array_equal(a[0], b[0], err_msg=text)
            np.testing.assert_array_equal(a[1], b[1], err_msg=text)
        assert native.tokenize(long_word) == py.tokenize(long_word)

    def test_small_max_seq_len_validated(self):
        with pytest.raises(ValueError):
            FasterTokenizer(VOCAB, max_seq_len=1)
        tok = self._tok(max_seq_len=2)
        ids, _ = tok("hello world")   # budget 0: only [CLS][SEP]
        v = {t: i for i, t in enumerate(VOCAB)}
        np.testing.assert_array_equal(np.asarray(ids.numpy())[0],
                                      [v["[CLS]"], v["[SEP]"]])
        with pytest.raises(ValueError):
            tok(["hello"], text_pair=["world"])   # pairs need >= 3

    def test_string_tensor_input(self):
        tok = self._tok(max_seq_len=8)
        st = StringTensor(["hello world", "the fox"])
        ids, _ = tok(st)
        assert ids.shape == [2, 8]


class TestStringTensor:
    def test_shape_and_ops(self):
        st = StringTensor([["Hello", "WORLD"], ["MiXeD", ""]])
        assert st.shape == [2, 2]
        lo = strings.lower(st)
        up = strings.upper(st)
        assert lo.numpy()[0, 1] == "world"
        assert up.numpy()[1, 0] == "MIXED"
        e = strings.empty([3])
        assert e.shape == [3] and e.numpy()[0] == ""

    def test_ascii_only_mode(self):
        st = StringTensor(["Ärger Ok"])   # Ärger
        lo = strings.lower(st, use_utf8_encoding=False)
        assert lo.numpy()[0] == "Ärger ok"   # non-ASCII untouched

    def test_type_check(self):
        with pytest.raises(TypeError):
            StringTensor([1, 2])


class TestFP8:
    def test_quantize_roundtrip(self):
        from paddle_tpu.incubate.nn.functional import (dequantize_fp8,
                                                       quantize_fp8)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(64, 64).astype(np.float32))
        q, s = quantize_fp8(x, format="e4m3")
        import jax.numpy as jnp
        assert q.numpy().dtype == jnp.float8_e4m3fn
        back = dequantize_fp8(q, s)
        err = np.abs(np.asarray(back.numpy()) - np.asarray(x.numpy()))
        # e4m3 has ~2 mantissa-bit relative precision
        assert err.max() < 0.1 * np.abs(np.asarray(x.numpy())).max()

    def test_fp8_linear_close_to_fp32(self):
        from paddle_tpu.incubate.nn.functional import fp8_linear
        rng = np.random.RandomState(1)
        x = paddle.to_tensor(rng.randn(8, 32).astype(np.float32))
        w = paddle.to_tensor(rng.randn(32, 16).astype(np.float32))
        b = paddle.to_tensor(rng.randn(16).astype(np.float32))
        out = fp8_linear(x, w, bias=b)
        ref = np.asarray(x.numpy()) @ np.asarray(w.numpy()) + \
            np.asarray(b.numpy())
        got = np.asarray(out.numpy(), np.float32)
        # fp8 per-tensor scaling: relative error a few percent
        denom = np.abs(ref).max()
        assert np.abs(got - ref).max() / denom < 0.08
        import jax.numpy as jnp
        assert out.numpy().dtype == jnp.bfloat16

    def test_e5m2_format(self):
        from paddle_tpu.incubate.nn.functional import quantize_fp8
        import jax.numpy as jnp
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        q, s = quantize_fp8(x, format="e5m2")
        assert q.numpy().dtype == jnp.float8_e5m2
        with pytest.raises(ValueError):
            quantize_fp8(x, format="e3m4")


class TestFP8DelayedScaling:
    def test_scale_is_delayed(self):
        """The scale used for call N comes from the amax HISTORY, not
        the current batch: after seeing amax=8, a smaller batch still
        quantizes with 8/fmax."""
        from paddle_tpu.incubate.nn.functional import (
            fp8_delayed_state, quantize_fp8_delayed)
        st = fp8_delayed_state(history_len=4)
        x1 = paddle.to_tensor(np.array([[8.0, -2.0]], np.float32))
        q1, s1, st = quantize_fp8_delayed(x1, st)
        # empty history: falls back to current amax
        np.testing.assert_allclose(float(s1.numpy()), 8.0 / 448.0,
                                   rtol=1e-6)
        x2 = paddle.to_tensor(np.array([[1.0, -0.5]], np.float32))
        q2, s2, st = quantize_fp8_delayed(x2, st)
        # history holds amax=8 -> delayed scale, not 1/448
        np.testing.assert_allclose(float(s2.numpy()), 8.0 / 448.0,
                                   rtol=1e-6)
        hist = np.asarray(st["amax_history"].numpy())
        assert hist[0] == 1.0 and hist[1] == 8.0

    def test_history_rolls_out(self):
        from paddle_tpu.incubate.nn.functional import (
            fp8_delayed_state, quantize_fp8_delayed)
        st = fp8_delayed_state(history_len=2)
        big = paddle.to_tensor(np.array([16.0], np.float32))
        small = paddle.to_tensor(np.array([2.0], np.float32))
        _, _, st = quantize_fp8_delayed(big, st)
        _, _, st = quantize_fp8_delayed(small, st)
        _, _, st = quantize_fp8_delayed(small, st)
        # 16 has rolled out of the 2-entry window
        _, s, st = quantize_fp8_delayed(small, st)
        np.testing.assert_allclose(float(s.numpy()), 2.0 / 448.0,
                                   rtol=1e-6)

    def test_fp8_linear_layer(self):
        """FP8Linear forward approximates the fp32 linear and updates
        its amax-history buffers in place."""
        from paddle_tpu.incubate.nn import FP8Linear
        rng = np.random.RandomState(2)
        lyr = FP8Linear(32, 16)
        x = paddle.to_tensor(rng.randn(8, 32).astype(np.float32))
        h0 = np.asarray(lyr.x_amax_history.numpy()).copy()
        out = lyr(x)
        h1 = np.asarray(lyr.x_amax_history.numpy())
        assert not np.allclose(h0, h1), "buffer must update"
        ref = np.asarray(x.numpy()) @ np.asarray(lyr.weight.numpy()) + \
            np.asarray(lyr.bias.numpy())
        got = np.asarray(out.numpy(), np.float32)
        denom = np.abs(ref).max() + 1e-6
        assert np.abs(got - ref).max() / denom < 0.08
        # buffers ride the state dict (checkpointable)
        assert any("amax_history" in k for k in lyr.state_dict())
