"""Training & multichip observability (r9): trainer metrics contract
(frozen schema enabled + disabled), compile telemetry (wall time /
cost-analysis MFU / memory-analysis HBM on CPU), bit-identical
loss/grad_norm with observability on vs off, the host-vs-device gap
dump, the flight-recorder unification (monotonic clock, registry feed,
bounded dump retention, reset/configure, deterministic hang watchdog)
and ``tools/trace_summary.py --mode train``."""
import json
import os
import sys
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.models.llama import (LlamaConfig, init_params, loss_fn,
                                     param_shardings)
from paddle_tpu.distributed.trainer import (MeshConfig, Trainer,
                                            make_mesh)
from paddle_tpu.distributed.flight_recorder import (
    FlightRecorder, enable_flight_recorder, disable_flight_recorder,
    get_flight_recorder)
from paddle_tpu.observability import (MetricsRegistry, Observability,
                                      TRAIN_HISTOGRAMS)
from paddle_tpu.observability import timeline as timeline_mod

CFG = LlamaConfig(vocab_size=97, hidden_size=32, intermediate_size=64,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=32,
                  dtype=jnp.float32, remat=False)


def _trainer(**kw):
    mesh = make_mesh(MeshConfig(), devices=jax.devices()[:1])
    kw.setdefault("data_spec", P())
    kw.setdefault("lr", 1e-3)
    return Trainer(lambda p, t, l: loss_fn(p, t, l, CFG), mesh,
                   param_shardings(mesh, CFG), **kw)


def _batch(seed=0, b=2, s=8):
    rng = np.random.RandomState(seed)
    toks = jnp.asarray(rng.randint(0, 97, (b, s)), jnp.int32)
    return toks, jnp.asarray(np.roll(np.asarray(toks), -1, -1))


# -- trainer metrics schema contract ------------------------------------

BASE_KEYS = {"steps", "samples", "tokens", "wall_time_s",
             "samples_per_sec", "tokens_per_sec"}
OBS_KEYS = {"latency", "gauges", "compile", "compiles",
            "retrace_warnings", "mfu", "hbm", "host_gap_findings",
            "stall_dumps", "timeline_events", "timeline_dropped"}
HIST_KEYS = {"count", "unit", "mean", "min", "max", "p50", "p95", "p99"}


def test_trainer_metrics_schema_frozen_disabled():
    """The metric key set is a CONTRACT (bench output + downstream
    parsers): extend deliberately, never by accident."""
    tr = _trainer()
    state = tr.init_state(init_params(CFG, jax.random.key(0)))
    toks, labels = _batch()
    for _ in range(2):
        state, _ = tr.step(state, toks, labels)
    m = tr.metrics()
    assert set(m.keys()) == BASE_KEYS
    assert m["steps"] == 2
    assert m["samples"] == 4 and m["tokens"] == 32
    assert m["tokens_per_sec"] > 0


def test_trainer_metrics_schema_frozen_enabled():
    tr = _trainer(observability=True)
    state = tr.init_state(init_params(CFG, jax.random.key(0)))
    toks, labels = _batch()
    for _ in range(3):
        state, _ = tr.step(state, toks, labels)
    m = tr.metrics()
    assert set(m.keys()) == BASE_KEYS | OBS_KEYS
    assert set(m["latency"].keys()) == set(TRAIN_HISTOGRAMS)
    for name, snap in m["latency"].items():
        assert set(snap.keys()) == HIST_KEYS, name
    st = m["latency"]["step_ms"]
    assert st["count"] == 3
    assert st["p50"] <= st["p95"] <= st["p99"] <= st["max"]
    # loss/grad_norm gauges sampled every step
    for key in ("loss", "grad_norm"):
        assert m["gauges"][key]["last"] is not None, key


# -- compile telemetry / MFU / HBM (CPU smoke) --------------------------

def test_compile_telemetry_and_mfu_smoke():
    """cost_analysis FLOPs -> automatic MFU, memory_analysis -> HBM
    breakdown — on the CPU backend (the API contract; absolute numbers
    only mean something on real hardware)."""
    tr = _trainer(observability=True)
    state = tr.init_state(init_params(CFG, jax.random.key(0)))
    toks, labels = _batch()
    state, _ = tr.step(state, toks, labels)
    m = tr.metrics()
    assert m["compiles"] >= 1
    prog = m["compile"]["programs"]["train_step"]
    assert prog["count"] >= 1
    assert prog["wall_ms_total"] > 0
    assert prog["cost"]["flops"] > 0
    hbm = m["hbm"]
    assert hbm["argument_bytes"] > 0
    assert hbm["total_bytes"] > 0
    assert set(hbm) >= {"argument_bytes", "output_bytes", "temp_bytes",
                        "total_bytes"}
    mfu = m["mfu"]
    assert mfu is not None
    assert mfu["flops_per_step_per_device"] == prog["cost"]["flops"]
    assert 0.0 <= mfu["mfu"] <= 1.0
    assert mfu["peak_flops_per_chip"] > 0
    # compile_ms histogram + timeline event recorded
    assert m["latency"]["compile_ms"]["count"] >= 1
    names = [e.name for e in tr.observability.timeline.events()]
    assert "compile" in names and "train_step" in names


def test_compile_watchdog_arms_on_reset():
    """reset_metrics() arms the compile watcher: a genuinely new batch
    signature after warmup warns (the train-step retrace watchdog); a
    steady signature stays silent."""
    tr = _trainer(observability=True)
    state = tr.init_state(init_params(CFG, jax.random.key(0)))
    toks, labels = _batch()
    # two warmup steps (one would do since the fp32 bias correction
    # fixed the x64 master promotion — kept at two so this test pins
    # the watchdog contract, not the warmup length)
    for _ in range(2):
        state, _ = tr.step(state, toks, labels)
    tr.reset_metrics()
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error", RuntimeWarning)
        state, _ = tr.step(state, toks, labels)   # steady: silent
    assert tr.metrics()["retrace_warnings"] == 0
    toks2, labels2 = _batch(b=4, s=8)             # new batch shape
    with pytest.warns(RuntimeWarning, match="after warmup"):
        state, _ = tr.step(state, toks2, labels2)
    assert tr.metrics()["retrace_warnings"] == 1
    # re-arming starts a fresh retrace window: the fixed leak's old
    # warnings must not haunt the next window's snapshot
    tr.reset_metrics()
    assert tr.metrics()["retrace_warnings"] == 0


# -- multi-device AOT sharding (the r12 step-2 failure, fixed r15) ------

def _md_trainer(**kw):
    mesh = make_mesh(MeshConfig(fsdp=2), devices=jax.devices()[:2])
    kw.setdefault("data_spec", P())
    kw.setdefault("lr", 1e-3)
    return Trainer(lambda p, t, l: loss_fn(p, t, l, CFG), mesh,
                   param_shardings(mesh, CFG), **kw)


def test_multi_device_observed_trainer_survives_step2_resharding():
    """The pre-existing failure recorded in the verify skill since r12:
    on a multi-device mesh, GSPMD propagation re-shards some state
    leaves in the step-1 OUTPUT and the observed path's AOT executable
    rejected them at step 2 ("input sharding(s) does not match"). The
    compiled-cache key now includes each leaf's sharding, so step 2 is
    one extra warmup compile at the propagated (fixed-point) layout —
    and losses stay bit-identical to the unobserved trainer."""
    runs = []
    for obs in (False, True):
        tr = _md_trainer(observability=obs)
        state = tr.init_state(init_params(CFG, jax.random.key(0)))
        losses = []
        for i in range(3):
            toks, labels = _batch(seed=i)
            state, m = tr.step(state, toks, labels)   # step 2 used to raise
            losses.append(float(m["loss"]))
        runs.append(losses)
        if obs:
            # one compile per GSPMD layout (initial + propagated),
            # stable afterwards; the clean path never demoted to jit
            assert tr.metrics()["compile"]["count"] == 2
            assert tr._aot_fallback is False
    assert runs[0] == runs[1]


@pytest.mark.slow
def test_observed_step_falls_back_to_jit_on_sharding_reject(
        monkeypatch):
    """Belt-and-braces path: if a backend still rejects the committed
    shardings at call time, the observed step demotes to the plain jit
    path with a ONE-TIME warning instead of killing the train loop —
    and the math is unchanged (same jitted program)."""
    tr = _trainer(observability=True)
    ref = _trainer()
    state = tr.init_state(init_params(CFG, jax.random.key(2)))
    rstate = ref.init_state(init_params(CFG, jax.random.key(2)))
    toks, labels = _batch()

    def reject(self, tree, lr, staged):
        def boom(*a, **k):
            raise ValueError(
                "Compiled object called with input sharding(s) does "
                "not match the sharding(s) the computation was "
                "compiled with")
        return boom, 0.0

    monkeypatch.setattr(Trainer, "_compiled_for", reject)
    with pytest.warns(RuntimeWarning, match="falling back"):
        state, m = tr.step(state, toks, labels)
    assert tr._aot_fallback is True
    rstate, rm = ref.step(rstate, toks, labels)
    assert float(m["loss"]) == float(rm["loss"])
    # demoted: later steps run the jit path silently
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error", RuntimeWarning)
        state, m2 = tr.step(state, toks, labels)
    rstate, rm2 = ref.step(rstate, toks, labels)
    assert float(m2["loss"]) == float(rm2["loss"])
    assert tr.metrics()["latency"]["step_ms"]["count"] == 2


# -- numerics: observability must not change the math -------------------

def test_bit_identical_loss_with_observability_on_vs_off():
    """10 steps, same init, same batches: loss and grad_norm must be
    BIT-identical with observability on vs off (the observed step runs
    the same jitted program through lower().compile())."""
    results = []
    for obs in (False, True):
        tr = _trainer(observability=obs)
        state = tr.init_state(init_params(CFG, jax.random.key(1)))
        run = []
        for i in range(10):
            toks, labels = _batch(seed=i)
            state, m = tr.step(state, toks, labels)
            run.append((float(m["loss"]), float(m["grad_norm"])))
        results.append(run)
    assert results[0] == results[1]   # exact float equality, all steps


# -- host-vs-device gap detector ----------------------------------------

def test_host_gap_dump_on_forced_per_step_staging(tmp_path,
                                                  monkeypatch):
    """The llama failure mode, synthesized: force the staging phase to
    dwarf the device wait and the detector must emit a flight-recorder
    dump naming the phase split."""
    dump = tmp_path / "gap.json"
    obs = Observability(stall_dump_path=str(dump),
                        histograms=TRAIN_HISTOGRAMS)
    tr = _trainer(observability=obs, host_gap_factor=1.5,
                  host_gap_min_ms=5.0)
    orig = Trainer._stage_batch

    def slow_stage(self, b):
        time.sleep(0.01)          # the forced per-step h2d residual
        return orig(self, b)

    monkeypatch.setattr(Trainer, "_stage_batch", slow_stage)
    state = tr.init_state(init_params(CFG, jax.random.key(0)))
    toks, labels = _batch()
    for _ in range(2):
        state, _ = tr.step(state, toks, labels)
    m = tr.metrics()
    assert m["host_gap_findings"] >= 1
    assert m["stall_dumps"] >= 1
    assert dump.exists()
    # reset_metrics restarts the gap window: warmup findings must not
    # pollute (or dump-starve) the measured window
    tr.reset_metrics()
    assert tr.metrics()["host_gap_findings"] == 0
    assert tr._gap.dumps == 0
    report = json.loads(dump.read_text())
    assert "host-vs-device gap" in report["reason"]
    split = report["scheduler"]["phase_split"]
    assert split["stage_ms"] > split["device_wait_ms"]
    # the gap event is on the timeline too
    assert any(e.name == "host_gap"
               for e in tr.observability.timeline.events())


def test_no_gap_dump_on_healthy_steps():
    tr = _trainer(observability=True)   # default 4x/50ms thresholds
    state = tr.init_state(init_params(CFG, jax.random.key(0)))
    toks, labels = _batch()
    state, _ = tr.step(state, toks, labels)
    staged = tuple(tr._stage_batch(b) for b in (toks, labels))
    for _ in range(3):
        state, _ = tr.step(state, *staged)   # pre-staged: no h2d
    # tiny model on CPU: steps are fast, min_wall_ms gates the detector
    assert tr.metrics()["host_gap_findings"] == 0
    assert tr.metrics()["stall_dumps"] == 0


# -- disabled mode: zero overhead ---------------------------------------

def test_disabled_mode_no_event_objects_no_extra_sync(monkeypatch):
    """observability=False must not allocate a single TimelineEvent or
    Observability object, and must not add a block_until_ready sync."""
    def boom(*a, **k):
        raise AssertionError("allocated in disabled mode")
    monkeypatch.setattr(timeline_mod.TimelineEvent, "__init__", boom)
    monkeypatch.setattr(Observability, "__init__", boom)
    monkeypatch.setattr(jax, "block_until_ready", boom)
    tr = _trainer()
    assert tr.observability is None
    state = tr.init_state(init_params(CFG, jax.random.key(0)))
    toks, labels = _batch()
    state, m = tr.step(state, toks, labels)
    assert np.isfinite(float(m["loss"]))
    mm = tr.metrics()
    assert "latency" not in mm and "gauges" not in mm
    with pytest.raises(RuntimeError, match="disabled"):
        tr.export_trace("/tmp/never.json")
    with pytest.raises(RuntimeError, match="disabled"):
        tr.write_timeline("/tmp/never.jsonl")


# -- prefetch queue-depth gauge -----------------------------------------

def test_prefetch_queue_depth_gauge():
    tr = _trainer(observability=True)
    state = tr.init_state(init_params(CFG, jax.random.key(0)))
    rng = np.random.RandomState(3)

    def batches():
        for _ in range(4):
            toks = rng.randint(0, 97, (2, 8)).astype(np.int32)
            yield toks, np.roll(toks, -1, -1)

    for toks, labels in tr.prefetch(batches()):
        state, _ = tr.step(state, toks, labels)
    g = tr.metrics()["gauges"]
    assert "prefetch_queue_depth" in g
    assert g["prefetch_queue_depth"]["last"] is not None


# -- exports ------------------------------------------------------------

def test_trainer_chrome_and_jsonl_exports(tmp_path):
    tr = _trainer(observability=True)
    state = tr.init_state(init_params(CFG, jax.random.key(0)))
    toks, labels = _batch()
    for _ in range(3):
        state, _ = tr.step(state, toks, labels)
    trace_path = tmp_path / "train_trace.json"
    tr.export_trace(str(trace_path))
    trace = json.loads(trace_path.read_text())
    evs = trace["traceEvents"]
    assert any(e.get("ph") == "X" and e.get("name") == "train_step"
               for e in evs)
    assert any(e.get("ph") == "C" and e.get("name") == "loss"
               for e in evs)
    jsonl_path = tmp_path / "train_tl.jsonl"
    tr.write_timeline(str(jsonl_path))
    lines = [json.loads(ln)
             for ln in jsonl_path.read_text().splitlines()]
    assert lines[0]["kind"] == "meta"
    assert lines[0]["mode"] == "train"
    assert "mesh" in lines[0]
    steps = [ln for ln in lines if ln.get("name") == "train_step"]
    assert len(steps) == 3
    for s in steps:
        assert {"stage_ms", "dispatch_ms", "sync_ms",
                "dur_ms"} <= set(s)


# -- trace_summary --mode train -----------------------------------------

def _import_trace_summary():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import trace_summary
    finally:
        sys.path.pop(0)
    return trace_summary


def test_trace_summary_train_mode_canned(tmp_path):
    """--mode train on a canned timeline: per-phase breakdown, per-step
    host-vs-device gap, top-N slowest, compile log."""
    path = tmp_path / "train.jsonl"
    rows = [{"kind": "meta", "schema": 1, "mode": "train",
             "mesh": {"dp": 1}, "events": 5, "dropped": 0},
            {"kind": "event", "name": "compile", "t_ns": 0,
             "dur_ms": 900.0, "program": "train_step", "count": 1},
            {"kind": "event", "name": "train_step", "t_ns": 1, "step": 1,
             "dur_ms": 3400.0, "stage_ms": 3200.0, "dispatch_ms": 10.0,
             "sync_ms": 190.0},
            {"kind": "event", "name": "train_step", "t_ns": 2, "step": 2,
             "dur_ms": 210.0, "stage_ms": 5.0, "dispatch_ms": 5.0,
             "sync_ms": 200.0},
            # fast step: huge host/device ratio but tiny wall — must
            # NOT count as host-bound (the live detector's min_wall_ms
            # predicate, mirrored offline)
            {"kind": "event", "name": "train_step", "t_ns": 5, "step": 3,
             "dur_ms": 5.0, "stage_ms": 4.0, "dispatch_ms": 1.0,
             "sync_ms": 0.0},
            {"kind": "event", "name": "host_gap", "t_ns": 3, "step": 1,
             "host_ms": 3210.0, "device_wait_ms": 190.0},
            {"kind": "event", "name": "stall", "t_ns": 4,
             "reason": "host-vs-device gap: step 1 ..."}]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    ts = _import_trace_summary()
    meta, events, requests = ts.load(str(path))
    s = ts.summarize_train(meta, events, top=5)
    assert s["phases"]["stage_ms"]["count"] == 3
    assert s["phases"]["stage_ms"]["max_ms"] == 3200.0
    assert s["phases"]["sync_ms"]["mean_ms"] == pytest.approx(
        (190.0 + 200.0 + 0.0) / 3, rel=1e-6)
    gap = s["host_device_gap"]
    assert gap["steps"] == 3 and gap["host_bound_steps"] == 1
    # the genuinely host-bound step leads the list — NOT the fast step
    # whose near-zero sync produces a huge but meaningless ratio
    g1 = gap["worst"][0]
    assert g1["step"] == 1 and g1["host_bound"]
    assert g1["ratio"] == pytest.approx(3210.0 / 190.0, rel=0.01)
    g3 = next(g for g in gap["worst"] if g["step"] == 3)
    assert not g3["host_bound"]              # below min wall
    assert s["slowest_steps"][0]["step"] == 1
    assert s["compiles"][0]["program"] == "train_step"
    assert s["host_gap_events"] == 1 and len(s["stalls"]) == 1
    text = ts.render_train(s)
    assert "host-vs-device" in text and "stage_ms" in text
    # the CLI auto-detects train mode from the meta header
    import contextlib
    import io
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert ts.main([str(path), "--json"]) == 0
    assert json.loads(buf.getvalue())["host_device_gap"][
        "host_bound_steps"] == 1


# -- flight recorder: unification satellites ----------------------------

def test_flight_recorder_monotonic_clock_and_dump_clock_base(tmp_path):
    """CommTask timestamps ride the shared monotonic clock (they line
    up with the timeline), and dumps carry the wall/monotonic base pair
    so absolute times are recoverable."""
    dump = tmp_path / "fr.json"
    rec = enable_flight_recorder(timeout=3600.0, dump_path=str(dump))
    try:
        t_before = Observability.now()
        task = rec.begin("all_reduce", "dp", (4,), "float32")
        rec.end(task)
        t_after = Observability.now()
        assert t_before <= task.start_ts <= task.end_ts <= t_after
        # the monotonic domain, not the wall clock: a regression to
        # time.time() would put start_ts ~epoch-sized seconds away
        assert abs(task.start_ts - t_before) < 60.0
        rec.dump(reason="clock test")
        report = json.loads(dump.read_text())
        clock = report["clock"]
        assert {"wall", "monotonic", "monotonic_at_dump"} <= set(clock)
        # reconstructed absolute start lands within a minute of now
        abs_start = clock["wall"] + (task.start_ts - clock["monotonic"])
        assert abs(abs_start - time.time()) < 60.0
        assert report["entries"][0]["op"] == "all_reduce"
    finally:
        disable_flight_recorder()


def test_flight_recorder_dump_retention(tmp_path):
    """Successive dumps must not clobber the first report; past
    max_dumps nothing new is written (counted instead)."""
    dump = tmp_path / "hang.json"
    rec = FlightRecorder(timeout=3600.0, dump_path=str(dump),
                         max_dumps=3)
    rec.enabled = True
    t = rec.begin("all_reduce", "dp", (8,), "float32")
    rec.end(t)
    p0 = rec.dump(reason="first")
    p1 = rec.dump(reason="second")
    p2 = rec.dump(reason="third")
    assert p0 == str(dump)
    assert p1 == str(tmp_path / "hang.1.json")
    assert p2 == str(tmp_path / "hang.2.json")
    assert json.loads(dump.read_text())["reason"] == "first"
    assert json.loads((tmp_path / "hang.1.json").read_text())[
        "reason"] == "second"
    # beyond the cap: suppressed, not written
    p3 = rec.dump(reason="fourth")
    assert p3 == "" and rec.dumps_suppressed == 1
    assert not (tmp_path / "hang.3.json").exists()


def test_flight_recorder_dump_log_survives_reenable(tmp_path):
    """The dump log must survive reset()/re-enable: forgetting written
    files would hand the next hang the FIRST report's path to clobber
    — the overwrite bug this PR fixes, via the re-enable door."""
    dump = tmp_path / "hang.json"
    rec = enable_flight_recorder(timeout=3600.0, dump_path=str(dump))
    try:
        t = rec.begin("all_reduce", "dp", (4,), "float32")
        rec.end(t)
        assert rec.dump(reason="first") == str(dump)
        enable_flight_recorder(timeout=3600.0, dump_path=str(dump))
        t = rec.begin("all_reduce", "dp", (4,), "float32")
        rec.end(t)
        assert rec.dump(reason="second") == str(tmp_path / "hang.1.json")
        assert json.loads(dump.read_text())["reason"] == "first"
    finally:
        disable_flight_recorder()


def test_flight_recorder_reenable_keeps_pending_task(tmp_path):
    """enable_flight_recorder routes through configure()/reset(): an
    in-flight task survives a re-enable (its end() still lands, the
    watchdog can still catch it hanging)."""
    rec = enable_flight_recorder(timeout=3600.0)
    try:
        task = rec.begin("all_gather", "tp", (16,), "float32")
        assert task is not None and task.pending
        # re-enable with new knobs: pending task must survive
        rec2 = enable_flight_recorder(
            timeout=1800.0, dump_path=str(tmp_path / "d.json"),
            capacity=64)
        assert rec2 is rec
        assert rec.timeout == 1800.0 and rec.capacity == 64
        live = rec.tasks()
        assert any(t.seq == task.seq and t.pending for t in live)
        rec.end(task)
        assert not task.pending
        assert [t for t in rec.tasks() if t.seq == task.seq][0].end_ts \
            is not None
        # completed history was cleared by the reset
        assert all(t.seq >= task.seq for t in rec.tasks())
    finally:
        disable_flight_recorder()


def test_flight_recorder_watchdog_fires_then_stays_silent(tmp_path):
    """Hang watchdog on a simulated pending collective: fires (writes
    the dump) while the task is stuck past the timeout, reports it only
    once, and stays silent after the task completes."""
    dump = tmp_path / "wd.json"
    rec = FlightRecorder(timeout=0.01, dump_path=str(dump))
    rec.enabled = True
    task = rec.begin("all_reduce", "dp", (1024,), "float32")
    time.sleep(0.03)                      # now pending > timeout
    assert rec.check_once() == 1          # fires: new hung task
    assert dump.exists()
    report = json.loads(dump.read_text())
    assert "pending" in report["reason"]
    assert report["scheduler"]["pending"] == 1
    assert report["timeline_tail"][0]["op"] == "all_reduce"
    assert rec.check_once() == 0          # same hang: reported once
    rec.end(task)
    time.sleep(0.02)
    assert rec.check_once() == 0          # completed: silent
    t2 = rec.begin("broadcast", None, (2,), "float32")
    rec.end(t2)
    assert rec.check_once() == 0          # fast op: silent


def test_flight_recorder_feeds_registry_and_chrome_track(tmp_path):
    """bind_flight_recorder: completed collectives feed per-(op, axis)
    latency histograms + bytes counters into the observability
    registry, and the chrome export gains the per-rank collective
    track."""
    import paddle_tpu.distributed as dist
    obs = Observability()
    rec = enable_flight_recorder(timeout=3600.0)
    try:
        obs.bind_flight_recorder(rec)
        t = paddle.to_tensor(np.ones((8,), np.float32))
        dist.all_reduce(t)
        dist.all_reduce(t)
        h = obs.registry.histograms.get("collective_all_reduce@world_ms")
        assert h is not None and h.count == 2
        assert obs.registry.counters["collective_calls"][
            "all_reduce@world"] == 2
        assert obs.registry.counters["collective_bytes"][
            "all_reduce@world"] == 2 * 8 * 4
        obs.timeline.record("decode_step", dur_ms=1.0)
        path = tmp_path / "trace.json"
        obs.export_chrome(str(path))
        evs = json.loads(path.read_text())["traceEvents"]
        colls = [e for e in evs if e.get("name") == "all_reduce@world"]
        assert len(colls) == 2
        assert all(e["tid"] == 1000 for e in colls)   # rank-0 track
    finally:
        disable_flight_recorder()


def test_flight_recorder_per_axis_histograms():
    from paddle_tpu.observability import MetricsRegistry as _MR
    reg = _MR()
    rec = FlightRecorder(timeout=3600.0)
    rec.enabled = True
    rec.bind(registry=reg)
    for axis in ("dp", "dp", "mp"):
        t = rec.begin("all_reduce", axis, (4,), "float32")
        rec.end(t)
    assert reg.histograms["collective_all_reduce@dp_ms"].count == 2
    assert reg.histograms["collective_all_reduce@mp_ms"].count == 1
    assert reg.counters["collective_bytes"]["all_reduce@dp"] == 2 * 16


# -- stall-dump retention bound (Observability side) --------------------

def test_observability_stall_dump_retention(tmp_path):
    obs = Observability(stall_dump_path=str(tmp_path / "s.json"),
                        max_stall_dumps=2)
    p0 = obs.stall_dump("one", {})
    p1 = obs.stall_dump("two", {})
    p2 = obs.stall_dump("three", {})
    assert p0 == str(tmp_path / "s.json")
    assert p1 == str(tmp_path / "s.1.json")
    assert p2 == "" and obs.stall_dumps_suppressed == 1
    # suppressed dumps count, without growing the log unboundedly
    assert len(obs.stall_dumps) == 2


def test_stderr_dumps_are_never_capped(capsys):
    """Console diagnostics must not go dark: with no dump_path, every
    hang report goes to stderr regardless of max_dumps (only written
    FILES count against the retention bound)."""
    rec = FlightRecorder(timeout=3600.0, max_dumps=2)
    rec.enabled = True
    for i in range(4):
        t = rec.begin("all_reduce", "dp", (4,), "float32")
        rec.end(t)
        assert rec.dump(reason=f"hang {i}") == ""
    assert rec.dumps_suppressed == 0
    assert capsys.readouterr().err.count("[stall-dump]") == 4


def test_reenable_clears_stale_dump_path(tmp_path, capsys):
    """enable_flight_recorder() with the default dump_path must clear a
    previous caller's path — a hang report must not land in a stale
    (possibly deleted) file instead of the console."""
    stale = tmp_path / "stale.json"
    rec = enable_flight_recorder(timeout=3600.0, dump_path=str(stale))
    try:
        rec2 = enable_flight_recorder(timeout=3600.0)   # defaults
        assert rec2.dump_path is None
        t = rec2.begin("all_reduce", "dp", (4,), "float32")
        rec2.end(t)
        assert rec2.dump(reason="post-reenable") == ""
        assert "[stall-dump]" in capsys.readouterr().err
        assert not stale.exists()
    finally:
        disable_flight_recorder()


# -- trainer + flight recorder unification ------------------------------

def test_trainer_reset_survives_bound_flight_recorder(tmp_path):
    """reset_metrics() must reset ONLY the trainer's own counters: the
    bound recorder's dict-valued collective counters live in the same
    adopted dict and collectives must keep working after a reset."""
    import paddle_tpu.distributed as dist
    tr = _trainer(observability=True)
    rec = enable_flight_recorder(timeout=3600.0)
    try:
        tr.observability.bind_flight_recorder(rec)
        state = tr.init_state(init_params(CFG, jax.random.key(0)))
        toks, labels = _batch()
        state, _ = tr.step(state, toks, labels)
        t = paddle.to_tensor(np.ones((4,), np.float32))
        dist.all_reduce(t)
        m = tr.metrics()
        assert m["collectives"]["calls"]["all_reduce@world"] == 1
        assert m["collectives"]["bytes"]["all_reduce@world"] == 16
        # the latency histograms are part of the public contract, not
        # dead data behind registry internals
        lat = m["collectives"]["latency_ms"]["all_reduce@world"]
        assert lat["count"] == 1 and set(lat) == HIST_KEYS
        # base schema grows exactly the conditional sub-dict
        assert set(m.keys()) == BASE_KEYS | OBS_KEYS | {"collectives"}
        tr.reset_metrics()
        dist.all_reduce(t)          # must not crash on a zeroed dict
        m = tr.metrics()
        assert m["steps"] == 0      # trainer window reset...
        assert m["collectives"]["calls"]["all_reduce@world"] == 2
        # ...recorder counters survived (cumulative, like trace counts)
    finally:
        disable_flight_recorder()


# -- the AdamW x64 bias-correction fix (the bug the compile telemetry
# -- found at runtime in r9; fixed at the source in this PR) -----------

def _legacy_adamw_update(grads, state, lr, b1=0.9, b2=0.95, eps=1e-8,
                         wd=0.1, grad_clip=1.0):
    """VERBATIM pre-fix _adamw_update math: `1 - b1 ** step` with an
    int32 step drops its weak type under the global x64 flag and
    promotes the master tree to float64. Kept as the reference for the
    bit-identical-in-f32 assertion and the auditor self-test."""
    params, master, mu, nu, step = state
    step = step + 1
    gnorm_sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                   for g in jax.tree_util.tree_leaves(grads))
    gnorm = jnp.sqrt(gnorm_sq)
    scale = jnp.minimum(1.0, grad_clip / jnp.maximum(gnorm, 1e-12)) \
        if grad_clip else 1.0

    def upd(g, m, mu_i, nu_i):
        g32 = g.astype(jnp.float32) * scale
        mu_n = b1 * mu_i.astype(jnp.float32) + (1 - b1) * g32
        nu_n = b2 * nu_i.astype(jnp.float32) + (1 - b2) * jnp.square(g32)
        mhat = mu_n / (1 - b1 ** step)
        vhat = nu_n / (1 - b2 ** step)
        m_n = m * (1.0 - lr * wd) - lr * mhat / (jnp.sqrt(vhat) + eps)
        return m_n, mu_n.astype(mu_i.dtype), nu_n.astype(nu_i.dtype)

    tl = jax.tree_util.tree_leaves
    treedef = jax.tree_util.tree_structure(grads)
    new_m, new_mu, new_nu = [], [], []
    for g, m, mi, ni in zip(tl(grads), tl(master), tl(mu), tl(nu)):
        a, b, c = upd(g, m, mi, ni)
        new_m.append(a)
        new_mu.append(b)
        new_nu.append(c)
    unf = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)  # noqa: E731
    master_n, mu_n, nu_n = unf(new_m), unf(new_mu), unf(new_nu)
    params_n = jax.tree_util.tree_map(
        lambda m, p: m.astype(p.dtype), master_n, params)
    return (params_n, master_n, mu_n, nu_n, step), gnorm


def _tiny_opt_state(key=0):
    rng = np.random.RandomState(key)
    mk = lambda *s: jnp.asarray(rng.randn(*s), jnp.float32)  # noqa: E731
    params = {"w": mk(8, 4), "b": mk(4)}
    master = jax.tree_util.tree_map(lambda v: v.astype(jnp.float32),
                                    params)
    mu = jax.tree_util.tree_map(jnp.zeros_like, master)
    nu = jax.tree_util.tree_map(jnp.zeros_like, master)
    return (params, master, mu, nu, jnp.zeros((), jnp.int32))


def test_adamw_fix_keeps_f32_state_under_x64():
    """The repo runs with jax_enable_x64 globally on (paddle int64 /
    float64 semantics) — exactly the config that promoted the pre-fix
    master tree to float64 after step 1."""
    from paddle_tpu.distributed.trainer import _adamw_update
    assert jax.config.jax_enable_x64        # the bug's precondition
    # the updates run JITTED, like the trainer's step: the weak type
    # survives eager execution (weak f64 defers to the f32 array) but
    # is dropped under tracing — the bug only exists in the compiled
    # step, which is why it took compile telemetry to find and why a
    # trace-level static auditor is the right tool to catch it
    fixed_fn = jax.jit(
        lambda g, s: _adamw_update(g, s, jnp.float32(1e-3)))
    state = _tiny_opt_state()
    g = jax.tree_util.tree_map(jnp.ones_like, state[0])
    for _ in range(3):
        state, _ = fixed_fn(g, state)
    for leaf in jax.tree_util.tree_leaves(state[1]):    # master
        assert leaf.dtype == jnp.float32
    assert state[4].dtype == jnp.int32                  # step
    # and the legacy math really does widen (the bug exists, the fix
    # is not vacuous)
    legacy_fn = jax.jit(
        lambda g, s: _legacy_adamw_update(g, s, jnp.float32(1e-3)))
    legacy, _ = legacy_fn(g, _tiny_opt_state())
    assert {str(leaf.dtype) for leaf in
            jax.tree_util.tree_leaves(legacy[1])} == {"float64"}


def test_adamw_fix_bit_identical_to_legacy_in_f32():
    """With x64 off the weak-typed legacy path already ran pow(f32,
    f32): the explicit fp32 bias correction must be the SAME program —
    bit-identical state after 5 steps, not merely close."""
    from jax.experimental import disable_x64
    from paddle_tpu.distributed.trainer import _adamw_update
    with disable_x64():
        s_new, s_old = _tiny_opt_state(1), _tiny_opt_state(1)
        for i in range(5):
            rng = np.random.RandomState(100 + i)
            g = {"w": jnp.asarray(rng.randn(8, 4), jnp.float32),
                 "b": jnp.asarray(rng.randn(4), jnp.float32)}
            s_new, gn_new = _adamw_update(g, s_new, jnp.float32(1e-3))
            s_old, gn_old = _legacy_adamw_update(g, s_old,
                                                 jnp.float32(1e-3))
        assert float(gn_new) == float(gn_old)
        for a, b in zip(jax.tree_util.tree_leaves(s_new),
                        jax.tree_util.tree_leaves(s_old)):
            assert a.dtype == b.dtype
            assert np.array_equal(np.asarray(a), np.asarray(b))


def test_adamw_single_compile_across_10_steps_with_x64():
    """The regression the fix buys back: one compile for the whole run
    (pre-fix, the step-1 master promotion changed the state signature
    and recompiled at step 2 inside every bench window)."""
    assert jax.config.jax_enable_x64
    tr = _trainer(observability=True)
    state = tr.init_state(init_params(CFG, jax.random.key(0)))
    toks, labels = _batch()
    losses = []
    for _ in range(10):
        state, m = tr.step(state, toks, labels)
        losses.append(float(m["loss"]))
    assert tr.metrics()["compiles"] == 1
    for leaf in jax.tree_util.tree_leaves(state.master):
        assert leaf.dtype == jnp.float32
    assert state.step.dtype == jnp.int32
    assert all(np.isfinite(losses))
