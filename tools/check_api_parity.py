#!/usr/bin/env python
"""API-parity diff against the reference (round-3 verdict Next #9).

Measures, not guesses: parses the reference's public registries —
``tensor_method_func`` (python/paddle/tensor/__init__.py) and the
top-level ``paddle.__all__`` (python/paddle/__init__.py) — and checks
each name against paddle_tpu's surface (top-level attr or Tensor
method). Exits nonzero if anything is missing and prints the list, so
the suite can gate on it (tests/test_tensor_breadth.py).

Annotated exclusions (reference names that are deliberately N/A here):
  none currently — as of round 4 both registries diff clean.
"""
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
REF = os.environ.get("PADDLE_REF", "/root/reference")

# Names whose reference semantics don't map to this framework, with the
# reason. Keep empty unless a future reference bump adds something truly
# CUDA-only; document the reason inline.
EXCLUDED: dict = {}


def _registry(path, pattern):
    src = open(path).read()
    m = re.search(pattern, src, re.S)
    # both quote styles: newer reference files (e.g. nn/quant) use
    # double quotes — matching only single quotes silently yields an
    # EMPTY registry, a vacuous "0 missing"
    return sorted(set(re.findall(r"['\"]([A-Za-z0-9_]+)['\"]",
                                 m.group(1))))


def main():
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor

    missing = {}
    tensor_fns = _registry(
        os.path.join(REF, "python/paddle/tensor/__init__.py"),
        r"tensor_method_func = \[(.*?)\]")
    missing["tensor_method_func"] = [
        n for n in tensor_fns
        if not (hasattr(paddle, n) or hasattr(Tensor, n))
        and n not in EXCLUDED]

    top = _registry(os.path.join(REF, "python/paddle/__init__.py"),
                    r"__all__ = \[(.*?)\]")
    missing["paddle.__all__"] = [n for n in top if not hasattr(paddle, n)
                                 and n not in EXCLUDED]

    # subsystem __all__ registries: module path -> our module
    import importlib
    for ref_py, mod_name in [
            ("python/paddle/nn/__init__.py", "paddle_tpu.nn"),
            ("python/paddle/nn/functional/__init__.py",
             "paddle_tpu.nn.functional"),
            ("python/paddle/nn/quant/__init__.py",
             "paddle_tpu.nn.quant"),
            ("python/paddle/linalg.py", "paddle_tpu.linalg"),
            ("python/paddle/fft.py", "paddle_tpu.fft"),
            ("python/paddle/signal.py", "paddle_tpu.signal"),
            ("python/paddle/sparse/__init__.py", "paddle_tpu.sparse"),
            ("python/paddle/vision/__init__.py", "paddle_tpu.vision"),
            ("python/paddle/geometric/__init__.py",
             "paddle_tpu.geometric"),
            ("python/paddle/amp/__init__.py", "paddle_tpu.amp"),
            ("python/paddle/static/__init__.py", "paddle_tpu.static"),
            ("python/paddle/metric/__init__.py", "paddle_tpu.metric"),
            ("python/paddle/distribution/__init__.py",
             "paddle_tpu.distribution"),
            ("python/paddle/optimizer/__init__.py",
             "paddle_tpu.optimizer"),
            ("python/paddle/io/__init__.py", "paddle_tpu.io"),
            ("python/paddle/distributed/__init__.py",
             "paddle_tpu.distributed"),
            ("python/paddle/audio/__init__.py", "paddle_tpu.audio"),
            ("python/paddle/audio/functional/__init__.py",
             "paddle_tpu.audio.functional"),
            ("python/paddle/jit/__init__.py", "paddle_tpu.jit"),
            ("python/paddle/profiler/__init__.py",
             "paddle_tpu.profiler"),
            ("python/paddle/nn/initializer/__init__.py",
             "paddle_tpu.nn.initializer"),
            ("python/paddle/vision/transforms/__init__.py",
             "paddle_tpu.vision.transforms"),
            ("python/paddle/vision/ops.py", "paddle_tpu.vision.ops"),
            ("python/paddle/vision/models/__init__.py",
             "paddle_tpu.vision.models"),
            ("python/paddle/autograd/__init__.py",
             "paddle_tpu.autograd"),
            ("python/paddle/framework/__init__.py",
             "paddle_tpu.framework"),
            ("python/paddle/regularizer.py", "paddle_tpu.regularizer"),
            ("python/paddle/inference/__init__.py",
             "paddle_tpu.inference"),
            ("python/paddle/onnx/__init__.py", "paddle_tpu.onnx"),
            ("python/paddle/utils/__init__.py", "paddle_tpu.utils"),
            ("python/paddle/incubate/__init__.py",
             "paddle_tpu.incubate"),
            ("python/paddle/text/__init__.py", "paddle_tpu.text"),
            ("python/paddle/incubate/nn/__init__.py",
             "paddle_tpu.incubate.nn"),
            ("python/paddle/incubate/nn/functional/__init__.py",
             "paddle_tpu.incubate.nn.functional"),
            ("python/paddle/distributed/fleet/__init__.py",
             "paddle_tpu.distributed.fleet"),
            ("python/paddle/sparse/nn/__init__.py",
             "paddle_tpu.sparse.nn"),
            ("python/paddle/vision/datasets/__init__.py",
             "paddle_tpu.vision.datasets"),
            ("python/paddle/audio/features/__init__.py",
             "paddle_tpu.audio.features"),
            ("python/paddle/audio/datasets/__init__.py",
             "paddle_tpu.audio.datasets")]:
        path = os.path.join(REF, ref_py)
        if not os.path.exists(path):
            continue
        try:
            names = _registry(path, r"__all__ = \[(.*?)\]")
        except AttributeError:
            continue   # module has no list-form __all__
        try:
            mod = importlib.import_module(mod_name)
        except ModuleNotFoundError:
            # attribute-style namespace (paddle.linalg lives on the
            # package, not as an importable submodule path)
            try:
                mod = paddle
                for part in mod_name.split(".")[1:]:
                    mod = getattr(mod, part)
            except AttributeError:
                rest = [n for n in names if n not in EXCLUDED]
                missing[mod_name] = (["<module missing entirely>"] + rest
                                     if rest else [])
                continue
        missing[mod_name] = [n for n in names if not hasattr(mod, n)
                             and not hasattr(paddle, n)
                             and n not in EXCLUDED]

    total = sum(len(v) for v in missing.values())
    for reg, names in missing.items():
        print(f"{reg}: {len(names)} missing"
              + (f": {names}" if names else ""))
    if EXCLUDED:
        print(f"excluded (annotated): {sorted(EXCLUDED)}")
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
