#!/usr/bin/env python
"""Kernel-geometry audit gate over every registered Pallas kernel.

Captures each kernel's launch geometry (grid, BlockSpecs, scratch, the
active VMEM budget) through ``ops/pallas/_util.audited_pallas_call`` at
the tiny + flagship serving/training shape classes, evaluates the index
maps concretely over the full grid, and proves: grid coverage
(GRID_FLOOR_DROP), block bounds (OOB_BLOCK), output-write injectivity
(WRITE_RACE), the pipelined VMEM window budget (VMEM_OVERCOMMIT),
kernel/launch arity (SCRATCH_MISMATCH), and the registry's
dispatch-key coverage (DISPATCH_KEY_GAP). Findings diff against the
committed baseline exactly like ``tools/program_audit.py``: NEW
findings fail the gate with exit 2, accepted ones pass, fixed ones
shrink the baseline on its next refresh.

Usage:
  python tools/kernel_audit.py                      # gate vs KERNEL_AUDIT_BASELINE.json
  python tools/kernel_audit.py --json out.json      # bank the findings doc
  python tools/kernel_audit.py --write-baseline     # freeze current findings
  python tools/kernel_audit.py --case fused_linear_ce --case decode_mlp_block@tiny
  python tools/kernel_audit.py --list               # case names
  python tools/kernel_audit.py --demo-regression    # inject the verbatim pre-fix
                                                    # non-divisor block_f kernel
                                                    # (gate must FAIL)

Exit codes: 0 clean (no new findings), 2 new findings, 3 bad
invocation or broken baseline. A kernel case that fails to trace, or a
declared launch the trace no longer captures, is itself a finding, so
2 covers those too.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

DEFAULT_BASELINE = os.path.join(_REPO, "KERNEL_AUDIT_BASELINE.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON (default: repo "
                         "KERNEL_AUDIT_BASELINE.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the diff: report findings, exit 2 on any")
    ap.add_argument("--write-baseline", action="store_true",
                    help="freeze current findings as the baseline and "
                         "exit 0")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full findings document to PATH")
    ap.add_argument("--case", action="append", default=None,
                    help="audit only these cases — an op name "
                         "(all its shape classes) or op@case "
                         "(repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="print case names and exit")
    ap.add_argument("--demo-regression", action="store_true",
                    help="also audit the pre-fix non-divisor block_f "
                         "kernel — the gate must fail (CI self-check)")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis.kernel_catalog import (
        KERNEL_CASE_NAMES, audit_kernels, build_demo_kernel_regression)
    if args.list:
        print("\n".join(KERNEL_CASE_NAMES + ("kernel_registry",)))
        return 0

    from paddle_tpu.analysis import (diff_findings, findings_to_json,
                                     load_baseline, write_baseline)

    if args.write_baseline and args.demo_regression:
        print("[kernel-audit] refusing --write-baseline with "
              "--demo-regression: the demo specimen must never become "
              "an accepted finding", file=sys.stderr)
        return 3
    if args.write_baseline and args.case \
            and os.path.realpath(args.baseline) \
            == os.path.realpath(DEFAULT_BASELINE):
        print("[kernel-audit] refusing --write-baseline for a --case "
              "subset over the shared baseline — audit the full "
              "catalog, or point --baseline at a scratch file",
              file=sys.stderr)
        return 3

    try:
        reports = audit_kernels(names=args.case)
    except ValueError as e:
        print(f"[kernel-audit] {e}", file=sys.stderr)
        return 3
    if args.demo_regression:
        reports.append(build_demo_kernel_regression())
    doc = findings_to_json(reports)

    if args.json:
        with open(args.json, "w") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")

    say = (lambda *a: None) if args.quiet else print
    for r in reports:
        extra = ""
        if r.meta.get("launches") is not None:
            extra = (f" ({r.meta['launches']} launch(es): "
                     f"{', '.join(r.meta.get('kernels', []))})")
        say(f"[kernel-audit] {r.program}: {len(r.findings)} "
            f"finding(s){extra}")
        for f in r.findings:
            say(f"  {f.severity:7s} {f.rule}/{f.code} @ {f.site}")
            say(f"          {f.message}")

    if args.write_baseline:
        write_baseline(reports, args.baseline)
        say(f"[kernel-audit] baseline written: {args.baseline} "
            f"({doc['summary']['findings']} accepted finding(s))")
        return 0

    if args.no_baseline:
        n = doc["summary"]["findings"]
        say(f"[kernel-audit] {n} finding(s), no baseline diff")
        return 2 if n else 0

    try:
        baseline = load_baseline(args.baseline)
    except FileNotFoundError:
        say(f"[kernel-audit] no baseline at {args.baseline} — treating "
            "every finding as new (write one with --write-baseline)")
        baseline = {"findings": {}}
    except ValueError as e:
        print(f"[kernel-audit] BROKEN BASELINE: {e}", file=sys.stderr)
        return 3

    new, fixed = diff_findings(reports, baseline)
    for fp in fixed:
        say(f"[kernel-audit] fixed vs baseline: {fp}")
    if fixed and not new:
        say("[kernel-audit] refresh the baseline with --write-baseline "
            "to shrink it")
    if new:
        print(f"[kernel-audit] GATE FAILED: {len(new)} new finding(s) "
              f"vs {args.baseline}:", file=sys.stderr)
        for f in new:
            print(f"  {f.severity:7s} {f.fingerprint}\n"
                  f"          {f.message}", file=sys.stderr)
        return 2
    say(f"[kernel-audit] gate clean: {doc['summary']['findings']} "
        f"finding(s), all accepted by baseline ({len(fixed)} fixed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
