#!/usr/bin/env python
"""Per-kernel bench regression gate against the banked BENCH trajectory.

The BENCH captures bank a ``kernels`` section with per-kernel
``us_pallas`` timings (bench.py ``bench_kernels``, persisted in
``BENCH_OPPORTUNISTIC.json`` and the per-round ``BENCH_rNN.json``
files). A tuning or fusion regression used to be invisible until a
reviewer eyeballed the numbers; this tool turns the trajectory into a
gate: a fresh capture whose ``us_pallas`` exceeds the banked best by
more than the threshold fails with exit code 1, the way audit findings
fail tools/program_audit.py.

Usage:
  python tools/kernel_bench_gate.py --capture fresh.json       # gate
  python tools/kernel_bench_gate.py --capture fresh.json --threshold 0.5
  python tools/kernel_bench_gate.py --list-banked              # show refs
  python tools/kernel_bench_gate.py --capture fresh.json --json out.json

``--capture`` accepts either a bare ``bench_kernels`` result (a dict
with ``cases``) or a full bench.py output document (the ``kernels`` key
is used). The banked reference for each kernel is the BEST (minimum)
``us_pallas`` across every banked capture — a regression is measured
against the trajectory's high-water mark, not last round's possibly-
already-regressed number.

bench.py runs this as a post-window step after the ``kernels`` config
(opt out with ``BENCH_KERNEL_GATE=0``; threshold via
``BENCH_KERNEL_GATE_THRESHOLD``, default 0.30 — device timing noise at
these microsecond scales makes tighter gates flaky).

Coverage is exactly ``bench_kernels``'s timed case set: the serving
decode kernels AND the fused training kernels (``fused_linear_ce``,
``fused_swiglu``, ``rms_norm_bwd`` — each timed over the full fwd+bwd
the trainer runs), so a training-fusion regression fails bench runs
the same way a decode regression does.

``--roofline`` switches the gated quantity from raw ``us_pallas`` to
the roofline observatory's ``achieved_bw_frac`` (bench.py prices every
case's modeled bytes against the measured time): a kernel whose
achieved-bandwidth fraction DROPS below the banked best by more than
the threshold (``BENCH_ROOFLINE_GATE_THRESHOLD``, default 0.30) fails,
and ``BENCH_ROOFLINE_GATE_FLOOR`` (default off) additionally flags any
kernel running far below its memory-bound roofline regardless of
history. ``--demo-regression`` self-checks the roofline gate with an
injected bandwidth collapse — it MUST exit nonzero.

Exit codes: 0 pass (or nothing comparable — no banked data / interpret
capture: a gate with no reference must not fail vacuously), 1 regression
over threshold, 3 bad invocation.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_THRESHOLD = 0.30


def _kernel_cases(doc):
    """A bench doc (full output, opportunistic bank, or bare kernels
    result) -> {kernel: us_pallas} for timed, non-interpret cases."""
    if not isinstance(doc, dict):
        return {}
    k = doc.get("kernels") if "cases" not in doc else doc
    if not isinstance(k, dict) or k.get("interpret"):
        return {}
    out = {}
    for name, case in (k.get("cases") or {}).items():
        us = case.get("us_pallas") if isinstance(case, dict) else None
        if isinstance(us, (int, float)) and us > 0:
            out[name] = float(us)
    return out


def _roofline_cases(doc):
    """A bench doc -> {kernel: achieved_bw_frac} for timed cases the
    roofline observatory priced (bench.py BENCH_ROOFLINE rows)."""
    if not isinstance(doc, dict):
        return {}
    k = doc.get("kernels") if "cases" not in doc else doc
    if not isinstance(k, dict) or k.get("interpret"):
        return {}
    out = {}
    for name, case in (k.get("cases") or {}).items():
        frac = case.get("achieved_bw_frac") \
            if isinstance(case, dict) else None
        if isinstance(frac, (int, float)) and frac > 0:
            out[name] = float(frac)
    return out


def _banked_docs(repo: str):
    """Every parseable banked BENCH document (BENCH_rNN files wrap the
    output under "parsed")."""
    paths = sorted(glob.glob(os.path.join(repo, "BENCH_r*.json")))
    paths += [os.path.join(repo, "BENCH_OPPORTUNISTIC.json")]
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        for d in (doc, doc.get("parsed") if isinstance(doc, dict)
                  else None):
            if d:
                yield path, d


def collect_banked(repo: str = _REPO):
    """Best (minimum) banked us_pallas per kernel across the BENCH
    trajectory, with the source file of each reference."""
    best, src = {}, {}
    for path, d in _banked_docs(repo):
        for name, us in _kernel_cases(d).items():
            if name not in best or us < best[name]:
                best[name] = us
                src[name] = os.path.basename(path)
    return best, src


def collect_banked_roofline(repo: str = _REPO):
    """Best (MAXIMUM) banked achieved_bw_frac per kernel — the
    trajectory's closest-to-roofline run is the reference a bandwidth
    regression is measured against."""
    best, src = {}, {}
    for path, d in _banked_docs(repo):
        for name, frac in _roofline_cases(d).items():
            if name not in best or frac > best[name]:
                best[name] = frac
                src[name] = os.path.basename(path)
    return best, src


def gate_capture(capture, threshold: float = DEFAULT_THRESHOLD,
                 repo: str = _REPO):
    """Diff a fresh capture against the banked trajectory.

    Returns a dict: ``status`` pass|regressed|no_reference, per-kernel
    ``regressions`` (over threshold), ``improved`` (faster than the
    banked best), ``new`` (no banked reference yet), ``checked``."""
    fresh = _kernel_cases(capture)
    banked, src = collect_banked(repo)
    res = {"threshold": threshold, "checked": 0, "regressions": {},
           "improved": {}, "new": sorted(set(fresh) - set(banked)),
           # banked keys this capture did NOT time: a shrunken capture
           # must be visible, not silently ungated (no-silent-caps)
           "skipped_banked": sorted(set(banked) - set(fresh)),
           "status": "pass"}
    if not fresh:
        res["status"] = "no_reference"
        res["note"] = ("capture has no timed us_pallas cases "
                       "(interpret mode or all errored)")
        return res
    if not banked:
        res["status"] = "no_reference"
        res["note"] = "no banked BENCH trajectory to diff against"
        return res
    if not set(fresh) & set(banked):
        # trajectory files EXIST and the capture timed kernels, yet not
        # one key lines up — a renamed case set would otherwise ride a
        # bare "pass" forever while gating nothing
        res["status"] = "no_reference"
        res["note"] = (f"no comparable kernel keys: capture has "
                       f"{sorted(fresh)}, banked trajectory has "
                       f"{sorted(banked)}")
        return res
    for name in sorted(set(fresh) & set(banked)):
        res["checked"] += 1
        ratio = fresh[name] / banked[name]
        entry = {"us_pallas": fresh[name], "banked_best": banked[name],
                 "banked_in": src[name], "ratio": round(ratio, 3)}
        if ratio > 1.0 + threshold:
            res["regressions"][name] = entry
        elif ratio < 1.0:
            res["improved"][name] = entry
    if res["regressions"]:
        res["status"] = "regressed"
    return res


def _diff_roofline(fresh, banked, src, threshold: float,
                   floor: float = 0.0):
    """Roofline-mode diff core (separated so --demo-regression can
    inject synthetic references): fresh/banked map kernel ->
    achieved_bw_frac; LOWER is worse, so a regression is
    ``fresh < banked_best * (1 - threshold)``. ``floor`` > 0
    additionally flags any fresh kernel below that absolute
    achieved-bandwidth fraction, banked or not."""
    res = {"mode": "roofline", "threshold": threshold, "floor": floor,
           "checked": 0, "regressions": {}, "improved": {},
           "new": sorted(set(fresh) - set(banked)),
           "skipped_banked": sorted(set(banked) - set(fresh)),
           "status": "pass"}
    if floor:
        for name, frac in sorted(fresh.items()):
            if frac < floor:
                res["regressions"][name] = {
                    "achieved_bw_frac": frac, "floor": floor,
                    "reason": "below_floor"}
    if not fresh:
        res["status"] = "no_reference"
        res["note"] = ("capture has no achieved_bw_frac rows "
                       "(interpret mode, BENCH_ROOFLINE=0, or untimed)")
        return res
    if not (set(fresh) & set(banked)):
        if res["regressions"]:
            res["status"] = "regressed"
            return res
        res["status"] = "no_reference"
        res["note"] = ("no banked achieved_bw_frac references to diff "
                       "against" if not banked else
                       f"no comparable kernel keys: capture has "
                       f"{sorted(fresh)}, banked trajectory has "
                       f"{sorted(banked)}")
        return res
    for name in sorted(set(fresh) & set(banked)):
        res["checked"] += 1
        ratio = fresh[name] / banked[name]
        entry = {"achieved_bw_frac": fresh[name],
                 "banked_best": banked[name], "banked_in": src[name],
                 "ratio": round(ratio, 3), "reason": "regressed_bw"}
        if ratio < 1.0 - threshold:
            res["regressions"].setdefault(name, entry)
        elif ratio > 1.0:
            res["improved"][name] = entry
    if res["regressions"]:
        res["status"] = "regressed"
    return res


def gate_roofline(capture, threshold: float = DEFAULT_THRESHOLD,
                  floor: float = 0.0, repo: str = _REPO):
    """Diff a fresh capture's achieved-bandwidth fractions against the
    banked trajectory's best (same SKIP semantics as the timing gate)."""
    fresh = _roofline_cases(capture)
    banked, src = collect_banked_roofline(repo)
    return _diff_roofline(fresh, banked, src, threshold, floor)


def build_demo_roofline_regression(threshold: float = DEFAULT_THRESHOLD):
    """Self-check: an injected bandwidth collapse (a kernel that banked
    at 62% of peak HBM bandwidth now achieving 5%) that MUST trip the
    roofline gate — proving the wiring end to end, kernel_audit.py
    --demo-regression style."""
    banked = {"decode_block_fused": 0.62}
    src = {"decode_block_fused": "<demo>"}
    fresh = {"decode_block_fused": 0.05}
    res = _diff_roofline(fresh, banked, src, threshold)
    res["demo"] = True
    return res


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--capture", metavar="PATH",
                    help="fresh bench JSON (full output or bare "
                         "kernels result)")
    ap.add_argument("--threshold", type=float, default=None,
                    help="allowed change vs the banked best (0.30 = "
                         "+30%% us_pallas growth, or -30%% "
                         "achieved_bw_frac drop with --roofline)")
    ap.add_argument("--roofline", action="store_true",
                    help="gate achieved_bw_frac (roofline observatory "
                         "rows) instead of raw us_pallas")
    ap.add_argument("--floor", type=float, default=float(
        os.environ.get("BENCH_ROOFLINE_GATE_FLOOR", "0")),
        help="with --roofline: flag any kernel below this absolute "
             "achieved-bandwidth fraction (default off)")
    ap.add_argument("--demo-regression", action="store_true",
                    help="roofline-gate self-check: inject a bandwidth "
                         "collapse that must fail the gate")
    ap.add_argument("--repo", default=_REPO,
                    help="repo dir holding the banked BENCH files")
    ap.add_argument("--json", metavar="PATH",
                    help="write the gate result document to PATH")
    ap.add_argument("--list-banked", action="store_true",
                    help="print the banked per-kernel references and "
                         "exit")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    say = (lambda *a: None) if args.quiet else print
    roofline = args.roofline or args.demo_regression
    if args.threshold is None:
        args.threshold = float(os.environ.get(
            "BENCH_ROOFLINE_GATE_THRESHOLD" if roofline
            else "BENCH_KERNEL_GATE_THRESHOLD", DEFAULT_THRESHOLD))

    if args.list_banked:
        banked, src = (collect_banked_roofline if roofline
                       else collect_banked)(args.repo)
        unit = "bw_frac" if roofline else "us"
        for name in sorted(banked):
            print(f"{name:24s} {banked[name]:10.4g} {unit}  "
                  f"({src[name]})")
        if not banked:
            print("(no banked kernel captures found)")
        return 0
    if args.threshold < 0:
        print("[kernel-gate] threshold must be >= 0", file=sys.stderr)
        return 3
    if args.demo_regression:
        if args.capture:
            print("[kernel-gate] --demo-regression refuses a real "
                  "--capture: the injected collapse would shadow it",
                  file=sys.stderr)
            return 3
        res = build_demo_roofline_regression(args.threshold)
    else:
        if not args.capture:
            print("[kernel-gate] --capture is required (or "
                  "--list-banked / --demo-regression)", file=sys.stderr)
            return 3
        try:
            with open(args.capture) as f:
                capture = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"[kernel-gate] cannot read capture "
                  f"{args.capture}: {e}", file=sys.stderr)
            return 3
        if roofline:
            res = gate_roofline(capture, threshold=args.threshold,
                                floor=args.floor, repo=args.repo)
        else:
            res = gate_capture(capture, threshold=args.threshold,
                               repo=args.repo)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=2, sort_keys=True)
            f.write("\n")

    if res["status"] == "no_reference":
        say(f"[kernel-gate] SKIP: {res.get('note', '')}")
        for name in res.get("skipped_banked", []):
            say(f"[kernel-gate] skipped banked key (no fresh timing): "
                f"{name}")
        return 0
    for name, e in res["regressions"].items():
        if "achieved_bw_frac" in e:
            ref = (f"vs banked {e['banked_best']:.4f} "
                   f"({e['banked_in']}) = {e['ratio']:.2f}x"
                   if "banked_best" in e
                   else f"below floor {e['floor']:.4f}")
            print(f"[kernel-gate] ROOFLINE REGRESSION {name}: "
                  f"achieved_bw_frac {e['achieved_bw_frac']:.4f} "
                  f"{ref} (threshold -{res['threshold']:.0%})",
                  file=sys.stderr)
        else:
            print(f"[kernel-gate] REGRESSION {name}: "
                  f"{e['us_pallas']:.1f}us "
                  f"vs banked {e['banked_best']:.1f}us "
                  f"({e['banked_in']}) = {e['ratio']:.2f}x (threshold "
                  f"{1 + res['threshold']:.2f}x)", file=sys.stderr)
    for name, e in res["improved"].items():
        if "achieved_bw_frac" in e:
            say(f"[kernel-gate] improved {name}: achieved_bw_frac "
                f"{e['achieved_bw_frac']:.4f} vs banked "
                f"{e['banked_best']:.4f} ({e['ratio']:.2f}x)")
        else:
            say(f"[kernel-gate] improved {name}: "
                f"{e['us_pallas']:.1f}us vs banked "
                f"{e['banked_best']:.1f}us ({e['ratio']:.2f}x)")
    if res["new"]:
        say(f"[kernel-gate] new kernels (no banked reference yet): "
            f"{', '.join(res['new'])}")
    if res["skipped_banked"]:
        # exactly which banked keys this run did NOT gate — a capture
        # that quietly stopped timing a kernel must say so
        say(f"[kernel-gate] banked keys skipped (not timed by this "
            f"capture): {', '.join(res['skipped_banked'])}")
    sign = "-" if res.get("mode") == "roofline" else "+"
    if res["status"] == "regressed":
        print(f"[kernel-gate] GATE FAILED: {len(res['regressions'])} "
              f"kernel(s) regressed past {sign}{res['threshold']:.0%}",
              file=sys.stderr)
        return 1
    say(f"[kernel-gate] gate clean: {res['checked']} kernel(s) within "
        f"{sign}{res['threshold']:.0%} of the banked trajectory")
    return 0


if __name__ == "__main__":
    sys.exit(main())
