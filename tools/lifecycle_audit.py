#!/usr/bin/env python
"""Lifecycle model-checker gate over the serving state machine.

Exhaustively explores the committed scope catalog
(``paddle_tpu.analysis.lifecycle.SCOPES``) — every interleaving of
submit/admit/prefill/decode/finish/preempt/expire/evict/spill/restore/
handoff/abort actions at small scopes, driving the REAL BlockManager /
PrefixCache / AdmissionQueue — and diffs the findings against the
committed baseline. NEW findings (not in the baseline) fail the gate
with exit code 2 and print a BFS-shortest, replayable counterexample
trace; the committed catalog is expected to hold 0 findings.

Usage:
  python tools/lifecycle_audit.py                      # gate vs LIFECYCLE_BASELINE.json
  python tools/lifecycle_audit.py --json out.json      # bank the full findings doc
  python tools/lifecycle_audit.py --write-baseline     # freeze current findings
  python tools/lifecycle_audit.py --scope coloc_prefix --scope disagg
  python tools/lifecycle_audit.py --list               # scope catalog + demo scopes
  python tools/lifecycle_audit.py --demo-regression    # re-inject the pre-fix r15
                                                       # starvation deadlock and the
                                                       # skipped-decref abort leak
                                                       # (gate must FAIL on both)
  python tools/lifecycle_audit.py --fuzz 200 --seed 7  # deterministic random walks
                                                       # instead of exhaustive BFS
  python tools/lifecycle_audit.py --dump-dir /tmp/lc   # counterexample traces as
                                                       # flight-recorder JSON dumps

Exit codes: 0 clean (no new findings), 2 new findings (or a demo
regression reproduced — the expected CI self-check failure), 3 bad
invocation, broken baseline, or a demo scope that FAILED to reproduce
its injected bug (the checker itself regressed).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

DEFAULT_BASELINE = os.path.join(_REPO, "LIFECYCLE_BASELINE.json")


def _dump_finding(f, dump_dir: str, idx: int) -> str:
    """One counterexample through the flight-recorder stall-dump
    format: the trace rides as the timeline tail (one entry per
    action), the end-state summary as the scheduler snapshot."""
    from paddle_tpu.observability.stall import dump_stall
    detail = f.detail
    tail = [{"event": "action", "step": i, "action": a, "label": lbl}
            for i, (a, lbl) in enumerate(zip(detail.get("trace", ()),
                                             detail.get("labels", ())))]
    path = os.path.join(dump_dir, f"lifecycle_ce_{idx}.json")
    return dump_stall(
        reason=f"lifecycle:{f.code}",
        scheduler=detail.get("state", {}),
        timeline_tail=tail, path=path,
        extra={"fingerprint": f.fingerprint, "message": f.message,
               "scope": detail.get("scope"),
               "injected_bug": detail.get("injected_bug")})


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON (default: repo "
                         "LIFECYCLE_BASELINE.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the diff: report findings, exit 2 on any")
    ap.add_argument("--write-baseline", action="store_true",
                    help="freeze current findings as the baseline and "
                         "exit 0")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full findings document to PATH")
    ap.add_argument("--scope", action="append", default=None,
                    help="explore only these catalog scopes (repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="print scope names (catalog + demo) and exit")
    ap.add_argument("--demo-regression", action="store_true",
                    help="also explore the two injected-bug demo scopes "
                         "— the gate must fail on each (CI self-check)")
    ap.add_argument("--fuzz", type=int, metavar="N", default=0,
                    help="run N deterministic random walks per scope "
                         "instead of exhaustive BFS")
    ap.add_argument("--seed", type=int, default=0,
                    help="fuzz seed (failing traces replay "
                         "byte-for-byte from the same seed)")
    ap.add_argument("--max-states", type=int, default=None,
                    help="override every scope's explored-state cap")
    ap.add_argument("--max-depth", type=int, default=None,
                    help="override every scope's BFS depth cap")
    ap.add_argument("--dump-dir", metavar="DIR", default=None,
                    help="write each counterexample as a flight-"
                         "recorder JSON dump under DIR")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis import lifecycle as lc
    from paddle_tpu.analysis import (diff_findings, findings_to_json,
                                     load_baseline, write_baseline)

    if args.list:
        for name, sc in lc.SCOPES.items():
            print(f"{name}: {sc.note}")
        for name, sc in lc.DEMO_SCOPES.items():
            print(f"{name} [demo, bug={sc.bug}]: {sc.note}")
        return 0

    if args.write_baseline and args.demo_regression:
        print("[lifecycle] refusing --write-baseline with "
              "--demo-regression: the injected bugs must never become "
              "accepted findings", file=sys.stderr)
        return 3
    if args.write_baseline and args.scope \
            and args.baseline == DEFAULT_BASELINE:
        print("[lifecycle] refusing --write-baseline for a --scope "
              "subset over the shared baseline — explore the full "
              "catalog, or point --baseline at a scratch file",
              file=sys.stderr)
        return 3

    names = args.scope or list(lc.SCOPES)
    unknown = [n for n in names
               if n not in lc.SCOPES and n not in lc.DEMO_SCOPES]
    if unknown:
        print(f"[lifecycle] unknown scope(s): {', '.join(unknown)} "
              f"(see --list)", file=sys.stderr)
        return 3
    scopes = [lc.SCOPES.get(n) or lc.DEMO_SCOPES[n] for n in names]
    demo_names = set()
    if args.demo_regression:
        for n, sc in lc.DEMO_SCOPES.items():
            if n not in names:
                scopes.append(sc)
            demo_names.add(n)

    say = (lambda *a: None) if args.quiet else print
    reports, results = [], []
    for sc in scopes:
        if args.fuzz > 0:
            res = lc.fuzz(sc, args.fuzz, seed=args.seed)
            say(f"[lifecycle] {sc.name}: {args.fuzz} walk(s), "
                f"{res.transitions} transitions, "
                f"{len(res.report.findings)} finding(s), "
                f"{res.wall_s:.1f}s")
        else:
            res = lc.explore(sc, max_states=args.max_states,
                             max_depth=args.max_depth)
            say(f"[lifecycle] {sc.name}: {res.states} states, "
                f"{res.transitions} transitions"
                f"{' (truncated)' if res.truncated else ''}, "
                f"{len(res.report.findings)} finding(s), "
                f"{res.wall_s:.1f}s")
        reports.append(res.report)
        results.append(res)
        for f in res.report.findings:
            say(f"  error   lifecycle/{f.code} @ {f.site}")
            say(f"          {f.message}")
            say(f"          trace ({len(f.detail['trace'])} actions): "
                f"{f.detail['labels']}")

    # CI self-check: a demo scope that no longer reproduces its
    # injected bug means the CHECKER regressed, not the code under test
    if args.demo_regression:
        for res in results:
            bug = res.report.meta.get("injected_bug")
            if bug and not res.report.findings:
                print(f"[lifecycle] SELF-CHECK FAILED: demo scope "
                      f"{res.report.program} (bug={bug}) produced no "
                      "finding — the checker lost its teeth",
                      file=sys.stderr)
                return 3

    doc = findings_to_json(reports)
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")
    if args.dump_dir:
        os.makedirs(args.dump_dir, exist_ok=True)
        i = 0
        for r in reports:
            for f in r.findings:
                p = _dump_finding(f, args.dump_dir, i)
                say(f"[lifecycle] counterexample dumped: {p}")
                i += 1

    if args.write_baseline:
        write_baseline(reports, args.baseline)
        say(f"[lifecycle] baseline written: {args.baseline} "
            f"({doc['summary']['findings']} accepted finding(s))")
        return 0

    if args.no_baseline:
        n = doc["summary"]["findings"]
        say(f"[lifecycle] {n} finding(s), no baseline diff")
        return 2 if n else 0

    try:
        baseline = load_baseline(args.baseline)
    except FileNotFoundError:
        say(f"[lifecycle] no baseline at {args.baseline} — treating "
            "every finding as new (write one with --write-baseline)")
        baseline = {"findings": {}}
    except ValueError as e:
        print(f"[lifecycle] BROKEN BASELINE: {e}", file=sys.stderr)
        return 3

    new, fixed = diff_findings(reports, baseline)
    for fp in fixed:
        say(f"[lifecycle] fixed vs baseline: {fp}")
    if new:
        print(f"[lifecycle] GATE FAILED: {len(new)} new finding(s) vs "
              f"{args.baseline}:", file=sys.stderr)
        for f in new:
            print(f"  error   {f.fingerprint}\n"
                  f"          {f.message}\n"
                  f"          trace: {f.detail.get('trace')}",
                  file=sys.stderr)
        return 2
    say(f"[lifecycle] gate clean: {doc['summary']['findings']} "
        f"finding(s), all accepted by baseline ({len(fixed)} fixed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
