#!/usr/bin/env python
"""Session-long opportunistic TPU bench capture.

The axon TPU tunnel wedges unpredictably (observed: ``jax.devices()``
hangs forever in client init), and waiting for the driver's single
end-of-round ``bench.py`` run to coincide with a healthy tunnel has
failed for two rounds straight. This prober runs for the whole session:

- every ``OPP_INTERVAL`` seconds it attempts the cheap device probe in a
  subprocess with a hard timeout (the wedge cannot be timed out
  in-process — client init blocks in C++);
- every attempt is appended to ``BENCH_PROBE_LOG.jsonl`` with a
  timestamp, so even a dead-all-day tunnel leaves evidence;
- the first time a probe succeeds it runs the full bench pack (resnet,
  llama-MFU, Pallas kernels compiled on chip, ernie decode, SD-UNet,
  BERT) config by config, persisting ``BENCH_OPPORTUNISTIC.json`` after
  every config so a mid-capture wedge still leaves the configs that
  finished;
- if the tunnel dies mid-pack, the remaining configs stay pending and
  capture resumes at the next healthy probe;
- ``bench.py`` serves the freshest captured result (flagged with its
  age) whenever its own live probe fails;
- the serving AND training configs run with the observability layer
  on, so each capture banks its full per-phase timeline JSONL
  (``BENCH_SERVING_TIMELINE.jsonl`` / ``BENCH_PREFIX_TIMELINE.jsonl`` /
  ``BENCH_TRAIN_TIMELINE.jsonl``, summarized by
  ``tools/trace_summary.py`` — ``--mode train`` for the trainer's
  stage/dispatch/sync phase split and host-vs-device gap report) next
  to this file — a short healthy TPU window yields step-time and
  TTFT/TPOT/queue-wait distributions, not point estimates.

Run detached:  nohup python tools/opportunistic_bench.py &
"""
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
import bench  # noqa: E402  (repo-root bench.py; only uses _spawn)

LOG = os.path.join(ROOT, "BENCH_PROBE_LOG.jsonl")
OUT = os.path.join(ROOT, "BENCH_OPPORTUNISTIC.json")

# (config, timeout_sec, max_attempts)
# Ordered by round-5 verdict priority: tunnel windows historically last
# ~45 min, so the north star (llama, with its blocks freshly tuned) and
# the never-measured ppyoloe must land before the breakdowns/sweeps.
PACK = [
    ("flash_tune", 900, 2),
    ("llama", 1500, 3),
    ("resnet50", 1500, 3),
    ("ppyoloe", 900, 2),
    ("bert", 900, 2),
    ("ernie_infer", 900, 2),
    ("paged_decode", 1500, 2),
    ("serving_engine", 1200, 2),
    ("serving_prefix_cache", 1200, 2),
    ("serving_prefill", 1200, 2),
    ("serving_quant", 1200, 2),
    # forced-host CPU: structure/parity evidence, cheap and tunnel-proof
    ("serving_tp", 900, 2),
    ("serving_disagg", 900, 2),
    ("serving_fleet", 900, 2),
    ("llama_ladder", 2700, 2),
    ("resnet50_sweep", 1500, 2),
    ("kernels", 1200, 3),
    ("resnet_breakdown", 1200, 2),
    ("llama_breakdown", 1200, 2),
    ("sd_unet", 900, 2),
]


def log(rec):
    rec = dict(rec, t=round(time.time(), 1),
               iso=time.strftime("%Y-%m-%dT%H:%M:%S"))
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")
    f2 = sys.stdout
    print(json.dumps(rec), file=f2, flush=True)


def load_results():
    try:
        with open(OUT) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def save_results(res):
    res["t"] = round(time.time(), 1)
    res["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(res, f, indent=1)
    os.replace(tmp, OUT)


def main():
    budget = float(os.environ.get("OPP_TOTAL_HOURS", "11")) * 3600
    interval = float(os.environ.get("OPP_INTERVAL", "180"))
    # the probe is a tiny device_put+add now (<20 s liveness); a wedged
    # tunnel should cost 20 s per attempt, not 150 s of the window
    probe_timeout = int(os.environ.get("OPP_PROBE_TIMEOUT", "20"))
    t0 = time.time()

    results = load_results()
    attempts = {name: 0 for name, _, _ in PACK}
    # OPP_FORCE="llama,kernels" re-measures those configs even though a
    # capture exists (e.g. after a perf fix); the old capture is only
    # replaced on SUCCESS
    force = [n.strip()
             for n in os.environ.get("OPP_FORCE", "").split(",")
             if n.strip()]
    pending = [name for name, _, _ in PACK
               if name in force
               or not (isinstance(results.get(name), dict)
                       and "error" not in results[name])]
    n_probe = 0
    log({"event": "start", "pending": pending})

    while time.time() - t0 < budget:
        n_probe += 1
        r = bench._spawn("probe", probe_timeout)
        ok = "error" not in r
        log({"event": "probe", "n": n_probe, "ok": ok,
             **({"device": r.get("device")} if ok
                else {"error": r.get("error", "")[:160]})})
        if not ok:
            time.sleep(interval)
            continue

        if not pending:
            log({"event": "done", "probes": n_probe})
            return 0

        name = pending[0]
        timeout = next(t for n, t, _ in PACK if n == name)
        max_att = next(m for n, _, m in PACK if n == name)
        attempts[name] += 1
        t_cfg = time.time()
        r = bench._spawn(name, timeout)
        ok_cfg = "error" not in r
        log({"event": "config", "name": name, "ok": ok_cfg,
             "secs": round(time.time() - t_cfg, 1),
             "attempt": attempts[name],
             **({"timeline_jsonl": r["timeline_jsonl"]}
                if ok_cfg and r.get("timeline_jsonl") else {}),
             **({} if ok_cfg else {"error": r.get("error", "")[:200]})})
        if ok_cfg or attempts[name] >= max_att:
            had_good = (isinstance(results.get(name), dict)
                        and "error" not in results[name])
            if ok_cfg or not had_good:
                # never clobber a previous good capture with an error
                results[name] = r
                results[name + "_iso"] = time.strftime("%Y-%m-%dT%H:%M:%S")
                save_results(results)
            pending.pop(0)
        # on failure below max attempts: re-probe first (the tunnel may
        # have wedged mid-config), then retry
        if not pending:
            log({"event": "pack_complete", "probes": n_probe})
            return 0

    log({"event": "gave_up", "probes": n_probe, "pending": pending})
    return 1


if __name__ == "__main__":
    sys.exit(main())
