#!/usr/bin/env python
"""Static program audit gate over the registered framework programs.

Audits the canonical program catalog (trainer step, fused optimizer
step, serving decode + prefill buckets, prefix-cache page copier,
collectives) with the ``paddle_tpu.analysis`` rule passes — dtype
promotion, donation, retrace hazards, collective consistency, constant
bloat — and diffs the findings against the committed baseline. NEW
findings (not in the baseline) fail the gate with exit code 2; findings
the baseline accepts pass silently; baseline entries that no longer
reproduce are reported as fixed (refresh with ``--write-baseline``).

Usage:
  python tools/program_audit.py                       # gate vs AUDIT_BASELINE.json
  python tools/program_audit.py --json out.json       # bank the full findings doc
  python tools/program_audit.py --write-baseline      # freeze current findings
  python tools/program_audit.py --program serving_decode --program train_step
  python tools/program_audit.py --list                # catalog program names
  python tools/program_audit.py --demo-regression     # inject the pre-fix AdamW
                                                      # program (gate must FAIL)
  python tools/program_audit.py --all                 # ALSO run the kernel-geometry
                                                      # audit (tools/kernel_audit.py)
                                                      # vs its own baseline; worst
                                                      # exit code wins

Exit codes: 0 clean (no new findings), 2 new findings, 3 bad
invocation or broken baseline file (unknown --program name, an
unreadable/mis-versioned baseline, or a --write-baseline combination
that would corrupt the accepted set). A program that fails to trace is
itself a finding, so 2 covers it.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

DEFAULT_BASELINE = os.path.join(_REPO, "AUDIT_BASELINE.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON (default: repo AUDIT_BASELINE.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the diff: report findings, exit 2 on any")
    ap.add_argument("--write-baseline", action="store_true",
                    help="freeze current findings as the baseline and exit 0")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full findings document to PATH")
    ap.add_argument("--program", action="append", default=None,
                    help="audit only these catalog programs (repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="print catalog program names and exit")
    ap.add_argument("--demo-regression", action="store_true",
                    help="also audit the pre-fix AdamW specimen — the "
                         "gate must fail (CI self-check)")
    ap.add_argument("--all", action="store_true", dest="all_audits",
                    help="also run the kernel-geometry audit "
                         "(tools/kernel_audit.py) vs "
                         "KERNEL_AUDIT_BASELINE.json and the lifecycle "
                         "model-checker gate (tools/lifecycle_audit.py) "
                         "vs LIFECYCLE_BASELINE.json; exits with the "
                         "worst of the three gates")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis.catalog import (CATALOG_PROGRAMS,
                                             build_catalog,
                                             build_demo_regression,
                                             build_demo_tp_regression)
    if args.list:
        print("\n".join(CATALOG_PROGRAMS))
        return 0

    from paddle_tpu.analysis import (audit_spec, diff_findings,
                                     findings_to_json, load_baseline,
                                     write_baseline)

    if args.write_baseline and args.demo_regression:
        # freezing the injected regression into the baseline would
        # make the CI self-check (--demo-regression must exit 2) pass
        # vacuously forever
        print("[audit] refusing --write-baseline with "
              "--demo-regression: the demo specimen must never become "
              "an accepted finding", file=sys.stderr)
        return 3
    if args.write_baseline and args.program \
            and args.baseline == DEFAULT_BASELINE:
        # a subset run only audited some programs; writing it over the
        # shared baseline would drop every other program's accepted
        # fingerprints
        print("[audit] refusing --write-baseline for a --program "
              "subset over the shared baseline — audit the full "
              "catalog, or point --baseline at a scratch file",
              file=sys.stderr)
        return 3

    def finish(rc: int) -> int:
        """--all: chain the kernel-geometry and lifecycle gates; worst
        exit wins."""
        if not args.all_audits:
            return rc
        import importlib.util
        # NOT --write-baseline: --all promises to RUN the chained
        # gates, never to silently freeze their current findings into
        # their baselines while refreshing the program one
        kargs = []
        for flag in ("no_baseline", "demo_regression", "quiet"):
            if getattr(args, flag):
                kargs.append("--" + flag.replace("_", "-"))
        for tool in ("kernel_audit", "lifecycle_audit"):
            spec = importlib.util.spec_from_file_location(
                tool, os.path.join(_REPO, "tools", tool + ".py"))
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            rc = max(rc, mod.main(list(kargs)))
        return rc

    try:
        specs = build_catalog(names=args.program)
    except ValueError as e:
        print(f"[audit] {e}", file=sys.stderr)
        return 3
    if args.demo_regression:
        # both injected specimens: the pre-fix AdamW (dtype rule) and
        # the mismatched-mesh-axis sharded decode body (collective
        # rule) — the gate must fail on each class, proving the rules
        # bite on real programs
        specs.append(build_demo_regression())
        specs.append(build_demo_tp_regression())
    reports = [audit_spec(s) for s in specs]
    doc = findings_to_json(reports)

    if args.json:
        with open(args.json, "w") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")

    say = (lambda *a: None) if args.quiet else print
    for r in reports:
        say(f"[audit] {r.program}: {len(r.findings)} finding(s)")
        for f in r.findings:
            say(f"  {f.severity:7s} {f.rule}/{f.code} @ {f.site}")
            say(f"          {f.message}")

    if args.write_baseline:
        write_baseline(reports, args.baseline)
        say(f"[audit] baseline written: {args.baseline} "
            f"({doc['summary']['findings']} accepted finding(s))")
        return finish(0)

    if args.no_baseline:
        n = doc["summary"]["findings"]
        say(f"[audit] {n} finding(s), no baseline diff")
        return finish(2 if n else 0)

    try:
        baseline = load_baseline(args.baseline)
    except FileNotFoundError:
        say(f"[audit] no baseline at {args.baseline} — treating every "
            "finding as new (write one with --write-baseline)")
        baseline = {"findings": {}}
    except ValueError as e:
        print(f"[audit] BROKEN BASELINE: {e}", file=sys.stderr)
        return 3

    new, fixed = diff_findings(reports, baseline)
    for fp in fixed:
        say(f"[audit] fixed vs baseline: {fp}")
    if fixed and not new:
        say("[audit] refresh the baseline with --write-baseline to "
            "shrink it")
    if new:
        print(f"[audit] GATE FAILED: {len(new)} new finding(s) vs "
              f"{args.baseline}:", file=sys.stderr)
        for f in new:
            print(f"  {f.severity:7s} {f.fingerprint}\n"
                  f"          {f.message}", file=sys.stderr)
        return finish(2)
    say(f"[audit] gate clean: {doc['summary']['findings']} finding(s), "
        f"all accepted by baseline ({len(fixed)} fixed)")
    return finish(0)


if __name__ == "__main__":
    sys.exit(main())
