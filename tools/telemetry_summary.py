#!/usr/bin/env python
"""Summarize a telemetry-plane JSONL (TelemetryPlane.write_jsonl or the
incremental ``jsonl_path`` bank).

Prints, without needing a Prometheus stack:

- the run header (namespace, sample cadence, sample/series counts,
  registered sources),
- one line per series: sample count, min / mean / max / last value and
  a unicode sparkline of the recent trend — the "did tokens/s sag over
  the window?" question answered from a file,
- the alert log: every burn-rate / anomaly fire with its rule,
  severity, metric, trigger value and threshold.

Usage:  python tools/telemetry_summary.py TELEMETRY.jsonl
            [--metric SUBSTR] [--top 40] [--json]

Exits 2 with a one-line error on a missing / empty / truncated file
(the trace_summary idiom — this CLI is scripted after bench runs).
"""
import argparse
import json
import os
import sys

BLOCKS = "▁▂▃▄▅▆▇█"


class TelemetryError(Exception):
    """A telemetry file the summary cannot work from — reported as ONE
    line on stderr with a nonzero exit, never a traceback."""


def load(path):
    meta, samples, alerts = {}, [], []
    try:
        f = open(path)
    except OSError as e:
        raise TelemetryError(
            f"cannot read telemetry file {path!r}: {e.strerror or e}")
    malformed = parsed = 0
    with f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                malformed += 1
                print(f"warning: skipping malformed line {ln}",
                      file=sys.stderr)
                continue
            kind = rec.get("kind")
            if kind == "telemetry_meta":
                meta = rec
                parsed += 1
            elif kind == "sample":
                samples.append(rec)
                parsed += 1
            elif kind == "alert":
                alerts.append(rec)
                parsed += 1
    if parsed == 0:
        if malformed:
            raise TelemetryError(
                f"{path}: no parseable telemetry records "
                f"({malformed} malformed line(s) — truncated JSONL?)")
        raise TelemetryError(
            f"{path}: empty telemetry file (no meta/sample/alert "
            "records)")
    return meta, samples, alerts


def sparkline(vals, width=32):
    vals = vals[-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi - lo <= 0:
        return BLOCKS[3] * len(vals)
    return "".join(BLOCKS[min(len(BLOCKS) - 1,
                              int((v - lo) / (hi - lo)
                                  * len(BLOCKS)))]
                   for v in vals)


def summarize(meta, samples, alerts, metric=None, top=40):
    series = {}
    for rec in samples:
        for sid, v in (rec.get("values") or {}).items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            series.setdefault(sid, []).append(float(v))
    if metric:
        series = {k: v for k, v in series.items() if metric in k}
    rows = []
    for sid in sorted(series):
        vals = series[sid]
        rows.append({"series": sid, "count": len(vals),
                     "min": round(min(vals), 4),
                     "mean": round(sum(vals) / len(vals), 4),
                     "max": round(max(vals), 4),
                     "last": round(vals[-1], 4),
                     "trend": sparkline(vals)})
    omitted = max(0, len(rows) - top) if top else 0
    if top:
        # keep the busiest series when capping — a capped listing of
        # all-zero constants would hide the interesting traces
        rows.sort(key=lambda r: (-r["count"], r["series"]))
        rows = sorted(rows[:top], key=lambda r: r["series"])
    return {"meta": {k: meta.get(k) for k in
                     ("namespace", "schema", "sample_every", "samples",
                      "series", "sources") if k in meta},
            "samples": len(samples),
            "series": rows, "series_omitted": omitted,
            "alerts": alerts}


def render(summary):
    m = summary["meta"]
    lines = [f"telemetry: {summary['samples']} samples, "
             f"{len(summary['series'])} series shown "
             f"({summary['series_omitted']} omitted), "
             f"sources {m.get('sources', '?')}, "
             f"sample_every={m.get('sample_every', '?')}"]
    if summary["series"]:
        w = max(len(r["series"]) for r in summary["series"])
        lines.append("")
        lines.append(f"{'series':<{w + 2}}{'n':>5}{'min':>12}"
                     f"{'mean':>12}{'max':>12}{'last':>12}  trend")
        for r in summary["series"]:
            lines.append(f"{r['series']:<{w + 2}}{r['count']:>5}"
                         f"{r['min']:>12}{r['mean']:>12}{r['max']:>12}"
                         f"{r['last']:>12}  {r['trend']}")
    alerts = summary["alerts"]
    lines.append("")
    if not alerts:
        lines.append("alerts: none")
    else:
        lines.append(f"alerts: {len(alerts)}")
        for a in alerts:
            lbl = ",".join(f"{k}={v}" for k, v in
                           sorted((a.get("labels") or {}).items()))
            lines.append(
                f"  [{a.get('severity', '?'):<6}] step "
                f"{a.get('step', '?')} {a.get('rule', '?')} on "
                f"{a.get('metric', '?')}{{{lbl}}}: value "
                f"{a.get('value')} vs threshold {a.get('threshold')}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="telemetry JSONL file")
    ap.add_argument("--metric", default=None,
                    help="only series whose id contains this substring")
    ap.add_argument("--top", type=int, default=40,
                    help="max series to list (default 40, 0 = all)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of a table")
    args = ap.parse_args(argv)
    try:
        meta, samples, alerts = load(args.path)
    except TelemetryError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    summary = summarize(meta, samples, alerts, metric=args.metric,
                        top=args.top)
    try:
        print(json.dumps(summary, indent=1) if args.json
              else render(summary))
    except BrokenPipeError:        # `... | head` closed stdout early
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


if __name__ == "__main__":
    sys.exit(main())
