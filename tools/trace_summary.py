#!/usr/bin/env python
"""Summarize a serving-timeline JSONL (ServingEngine.write_timeline).

Reads the structured per-phase JSONL the observability layer emits next
to each BENCH capture and prints, without needing a browser:

- per-phase breakdown: count / total / mean / max wall time per event
  name (decode_step, prefill_chunk, ...),
- the top-N slowest timed steps (the retrace or allocator hiccup is
  almost always one of these),
- per-request latency distributions (queue wait, TTFT, TPOT, e2e)
  with p50/p95/p99 computed from the request records.

Usage:  python tools/trace_summary.py TIMELINE.jsonl [--top 10] [--json]
"""
import argparse
import json
import sys


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def load(path):
    meta, events, requests = {}, [], []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                print(f"warning: skipping malformed line {ln}",
                      file=sys.stderr)
                continue
            kind = rec.get("kind")
            if kind == "meta":
                meta = rec
            elif kind == "event":
                events.append(rec)
            elif kind == "request":
                requests.append(rec)
    return meta, events, requests


def summarize(meta, events, requests, top=10):
    out = {"meta": {k: meta.get(k) for k in
                    ("schema", "events", "dropped", "capacity",
                     "num_blocks", "block_size") if k in meta}}

    phases = {}
    for ev in events:
        d = ev.get("dur_ms")
        if d is None:
            continue
        p = phases.setdefault(ev["name"], {"count": 0, "total_ms": 0.0,
                                           "max_ms": 0.0})
        p["count"] += 1
        p["total_ms"] += d
        p["max_ms"] = max(p["max_ms"], d)
    for p in phases.values():
        p["mean_ms"] = round(p["total_ms"] / p["count"], 3)
        p["total_ms"] = round(p["total_ms"], 3)
        p["max_ms"] = round(p["max_ms"], 3)
    out["phases"] = phases

    timed = [ev for ev in events if ev.get("dur_ms") is not None]
    timed.sort(key=lambda e: -e["dur_ms"])
    out["slowest_steps"] = timed[:top]

    lat = {}
    # warmup-flagged records (in flight across reset_metrics) are
    # excluded, matching the engine's own histogram exclusion
    live = [r for r in requests if not r.get("warmup")]
    for key in ("queue_wait_ms", "ttft_ms", "tpot_ms", "e2e_ms"):
        vals = sorted(r[key] for r in live
                      if r.get(key) is not None)
        if vals:
            lat[key] = {"count": len(vals),
                        "mean": round(sum(vals) / len(vals), 3),
                        "p50": round(_percentile(vals, 0.50), 3),
                        "p95": round(_percentile(vals, 0.95), 3),
                        "p99": round(_percentile(vals, 0.99), 3),
                        "max": round(vals[-1], 3)}
    out["request_latency"] = lat
    out["requests"] = len(requests)
    return out


def render(summary):
    lines = []
    m = summary["meta"]
    lines.append(f"timeline: {m.get('events', '?')} events "
                 f"({m.get('dropped', 0)} dropped), "
                 f"{summary['requests']} request records")
    lines.append("")
    lines.append(f"{'phase':<18}{'count':>8}{'total ms':>12}"
                 f"{'mean ms':>10}{'max ms':>10}")
    for name, p in sorted(summary["phases"].items(),
                          key=lambda kv: -kv[1]["total_ms"]):
        lines.append(f"{name:<18}{p['count']:>8}{p['total_ms']:>12}"
                     f"{p['mean_ms']:>10}{p['max_ms']:>10}")
    if summary["slowest_steps"]:
        lines.append("")
        lines.append(f"top {len(summary['slowest_steps'])} slowest steps:")
        for ev in summary["slowest_steps"]:
            extra = {k: v for k, v in ev.items()
                     if k not in ("kind", "name", "dur_ms", "t_ns")}
            lines.append(f"  {ev['dur_ms']:>10.3f} ms  {ev['name']:<16}"
                         f"{json.dumps(extra) if extra else ''}")
    if summary["request_latency"]:
        lines.append("")
        lines.append(f"{'latency':<16}{'count':>7}{'mean':>10}"
                     f"{'p50':>10}{'p95':>10}{'p99':>10}{'max':>10}")
        for name, s in summary["request_latency"].items():
            lines.append(f"{name:<16}{s['count']:>7}{s['mean']:>10}"
                         f"{s['p50']:>10}{s['p95']:>10}{s['p99']:>10}"
                         f"{s['max']:>10}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="timeline JSONL file")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest steps to list (default 10)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of a table")
    args = ap.parse_args(argv)
    meta, events, requests = load(args.path)
    summary = summarize(meta, events, requests, top=args.top)
    print(json.dumps(summary, indent=1) if args.json
          else render(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
