#!/usr/bin/env python
"""Summarize an observability-timeline JSONL.

Reads the structured per-phase JSONL the observability layer emits next
to each BENCH capture and prints, without needing a browser:

serving mode (ServingEngine.write_timeline /
DisaggregatedEngine.write_timeline):
- per-phase breakdown: count / total / mean / max wall time per event
  name (decode_step, prefill_chunk, ...),
- the top-N slowest timed steps (the retrace or allocator hiccup is
  almost always one of these),
- per-request latency distributions (queue wait, TTFT, TPOT, e2e)
  with p50/p95/p99 computed from the request records,
- a scheduler section when the SLO-admission machinery left traces:
  per-priority-class queue-wait percentiles (request records carry
  their class), preemption / resume / deadline-expiry counts, and the
  KV-handoff breakdown (count, bytes, extract/put/insert phase means)
  for disaggregated timelines.

train mode (Trainer.write_timeline, ``--mode train`` or auto-detected
from the meta header):
- per-phase breakdown of the step: stage (batch h2d), dispatch
  (compiled call), sync (device wait) totals/means,
- host-vs-device gap per step (host = stage + dispatch vs device =
  sync) with the worst offenders listed — the llama h2d-residual
  diagnosis, from a file,
- top-N slowest steps and every compile event (program, wall time).

Usage:  python tools/trace_summary.py TIMELINE.jsonl
            [--mode auto|serving|train] [--top 10] [--json]
"""
import argparse
import json
import sys


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


class TimelineError(Exception):
    """A timeline file the summary cannot work from — reported as ONE
    line on stderr with a nonzero exit, never a traceback (the CLI is
    scripted after bench runs; a stack trace in the log helps no
    one)."""


def load(path):
    meta, events, requests = {}, [], []
    try:
        f = open(path)
    except OSError as e:
        raise TimelineError(
            f"cannot read timeline file {path!r}: "
            f"{e.strerror or e}")
    malformed = 0
    parsed = 0
    with f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                malformed += 1
                print(f"warning: skipping malformed line {ln}",
                      file=sys.stderr)
                continue
            kind = rec.get("kind")
            if kind == "meta":
                meta = rec
                parsed += 1
            elif kind == "event":
                events.append(rec)
                parsed += 1
            elif kind == "request":
                requests.append(rec)
                parsed += 1
    if parsed == 0:
        if malformed:
            raise TimelineError(
                f"{path}: no parseable timeline records "
                f"({malformed} malformed line(s) — truncated JSONL?)")
        raise TimelineError(
            f"{path}: empty timeline file (no meta/event/request "
            "records)")
    return meta, events, requests


def summarize(meta, events, requests, top=10):
    out = {"meta": {k: meta.get(k) for k in
                    ("schema", "events", "dropped", "capacity",
                     "num_blocks", "block_size") if k in meta}}

    phases = {}
    for ev in events:
        d = ev.get("dur_ms")
        if d is None:
            continue
        p = phases.setdefault(ev["name"], {"count": 0, "total_ms": 0.0,
                                           "max_ms": 0.0})
        p["count"] += 1
        p["total_ms"] += d
        p["max_ms"] = max(p["max_ms"], d)
    for p in phases.values():
        p["mean_ms"] = round(p["total_ms"] / p["count"], 3)
        p["total_ms"] = round(p["total_ms"], 3)
        p["max_ms"] = round(p["max_ms"], 3)
    out["phases"] = phases

    timed = [ev for ev in events if ev.get("dur_ms") is not None]
    timed.sort(key=lambda e: -e["dur_ms"])
    out["slowest_steps"] = timed[:top]

    lat = {}
    # warmup-flagged records (in flight across reset_metrics) are
    # excluded, matching the engine's own histogram exclusion
    live = [r for r in requests if not r.get("warmup")]
    for key in ("queue_wait_ms", "ttft_ms", "tpot_ms", "e2e_ms"):
        vals = [r[key] for r in live if r.get(key) is not None]
        if vals:
            lat[key] = _dist(vals)
    out["request_latency"] = lat
    out["requests"] = len(requests)

    sched = summarize_scheduler(events, live)
    if sched is not None:
        out["scheduler"] = sched
    rt = summarize_routing(events)
    if rt is not None:
        out["routing"] = rt
    pre = summarize_prefill(events)
    if pre is not None:
        out["prefill"] = pre
    dec = summarize_decode(events, meta)
    if dec is not None:
        out["decode"] = dec
    return out


def summarize_decode(events, meta=None):
    """The decode section: per-variant step attribution from the
    ``decode_variant`` field the engines stamp on each decode_step
    event ("pallas_block" = single-launch block megakernel,
    "pallas_fused" = the two-kernel attn+MLP route, "unfused" = the
    composition) — so a capture says WHICH decode kernel its steps ran,
    mirroring the prefill ``variant`` attribution above. Returns None
    when no decode_step event carries the stamp (pre-r20 timelines
    keep their old summary shape)."""
    steps = [ev for ev in events if ev.get("name") == "decode_step"
             and ev.get("decode_variant") is not None]
    if not steps:
        return None
    per = {}
    for ev in steps:
        v = per.setdefault(str(ev["decode_variant"]), {
            "count": 0, "total_ms": 0.0, "max_ms": 0.0})
        v["count"] += 1
        d = ev.get("dur_ms") or 0.0
        v["total_ms"] += d
        v["max_ms"] = max(v["max_ms"], d)
    for v in per.values():
        v["mean_ms"] = round(v["total_ms"] / v["count"], 3)
        v["total_ms"] = round(v["total_ms"], 3)
        v["max_ms"] = round(v["max_ms"], 3)
    # roofline attribution (r21): the meta header carries the engine's
    # per-arm modeled bytes/step and the bandwidth-bound step-time
    # floor — pair each measured arm with its floor so the summary
    # prints "% of roofline", not just raw microseconds
    roof = (meta or {}).get("roofline") or {}
    rvars = roof.get("variants") or {}
    for name, v in per.items():
        r = rvars.get(name)
        if not r:
            continue
        v["bytes_per_step_modeled"] = r.get("bytes_per_step")
        v["step_us_at_peak_bw"] = r.get("step_us_at_peak_bw")
        floor_us = r.get("step_us_at_peak_bw")
        mean_us = v["mean_ms"] * 1e3
        if floor_us and mean_us > 0:
            v["roofline_frac"] = float(f"{floor_us / mean_us:.4g}")
    out = {"variants": per}
    if rvars:
        out["peak_hbm_bw"] = roof.get("peak_hbm_bw")
        out["peak_source"] = roof.get("peak_source")
    return out


def summarize_prefill(events):
    """The prefill section (r17): per-bucket chunk timings, ragged
    occupancy (valid vs bucket-padded tokens fed to the chunks), and
    fused-vs-ref variant attribution from the ``variant`` field the
    engines stamp on each prefill_chunk event. Returns None when the
    timeline has no bucketed prefill chunks (train mode / decode-only
    windows keep their old summary shape)."""
    chunks = [ev for ev in events if ev.get("name") == "prefill_chunk"
              and ev.get("bucket") is not None]
    if not chunks:
        return None
    per = {}
    for ev in chunks:
        b = per.setdefault(int(ev["bucket"]), {
            "count": 0, "total_ms": 0.0, "max_ms": 0.0,
            "valid_tokens": 0, "pad_tokens": 0})
        b["count"] += 1
        d = ev.get("dur_ms") or 0.0
        b["total_ms"] += d
        b["max_ms"] = max(b["max_ms"], d)
        n = int(ev.get("n") or 0)
        b["valid_tokens"] += n
        b["pad_tokens"] += max(int(ev["bucket"]) - n, 0)
    for b in per.values():
        b["mean_ms"] = round(b["total_ms"] / b["count"], 3)
        b["total_ms"] = round(b["total_ms"], 3)
        b["max_ms"] = round(b["max_ms"], 3)
        fed = b["valid_tokens"] + b["pad_tokens"]
        b["occupancy"] = round(b["valid_tokens"] / fed, 4) if fed \
            else None
    variants = {}
    for ev in chunks:
        v = ev.get("variant") or "unknown"
        variants[v] = variants.get(v, 0) + 1
    tot_valid = sum(b["valid_tokens"] for b in per.values())
    tot_pad = sum(b["pad_tokens"] for b in per.values())
    fed = tot_valid + tot_pad
    return {"per_bucket": {str(k): v for k, v in sorted(per.items())},
            "occupancy": round(tot_valid / fed, 4) if fed else None,
            "variants": variants}


def _dist(vals):
    vals = sorted(vals)
    return {"count": len(vals),
            "mean": round(sum(vals) / len(vals), 3),
            "p50": round(_percentile(vals, 0.50), 3),
            "p95": round(_percentile(vals, 0.95), 3),
            "p99": round(_percentile(vals, 0.99), 3),
            "max": round(vals[-1], 3)}


def summarize_scheduler(events, requests):
    """The SLO-admission section: per-priority-class queue-wait
    percentiles from the request records, preemption/resume/expiry
    counts from the timeline, and the KV-handoff phase breakdown
    (disaggregated engines). Returns None when the timeline carries no
    scheduler traces at all — plain FIFO timelines keep their old
    summary shape."""
    counts = {name: sum(1 for ev in events if ev.get("name") == name)
              for name in ("preempt", "resume", "expired", "handoff")}
    classes = sorted({r.get("priority") for r in requests
                      if r.get("priority") is not None})
    multi_class = len(classes) > 1
    if not any(counts.values()) and not multi_class:
        return None
    out = {"preemptions": counts["preempt"],
           "resumes": counts["resume"],
           "deadline_expired": counts["expired"]}
    per = {}
    for cls in classes:
        waits = [r["queue_wait_ms"] for r in requests
                 if r.get("priority") == cls
                 and r.get("queue_wait_ms") is not None]
        if waits:
            per[str(cls)] = _dist(waits)
    if per:
        out["per_class_queue_wait_ms"] = per
    hand = [ev for ev in events if ev.get("name") == "handoff"]
    if hand:
        h = {"count": len(hand),
             "bytes_total": sum(ev.get("bytes", 0) for ev in hand),
             "pages_total": sum(ev.get("pages", 0) for ev in hand),
             "handoff_ms": _dist([ev["dur_ms"] for ev in hand
                                  if ev.get("dur_ms") is not None])}
        for phase in ("extract_ms", "put_ms", "insert_ms"):
            vals = [ev[phase] for ev in hand if ev.get(phase) is not None]
            if vals:
                h[phase + "_mean"] = round(sum(vals) / len(vals), 3)
        out["handoff"] = h
    return out


def summarize_routing(events):
    """The fleet routing section: warm/cold/diverted counts, warm-hit
    ratio, and each replica's share of the routed requests. Returns
    None when the timeline carries no ``route`` events — single-engine
    timelines keep their old summary shape."""
    routes = [ev for ev in events if ev.get("name") == "route"]
    if not routes:
        return None
    per = {}
    warm = diverted = 0
    for ev in routes:
        rep = str(ev.get("replica"))
        d = per.setdefault(rep, {"routed": 0, "warm": 0, "diverted": 0})
        d["routed"] += 1
        if ev.get("matched_tokens", 0):
            d["warm"] += 1
            warm += 1
        if ev.get("diverted"):
            d["diverted"] += 1
            diverted += 1
    n = len(routes)
    for d in per.values():
        d["share"] = round(d["routed"] / n, 4)
    return {"requests": n, "warm": warm, "cold": n - warm,
            "diverted": diverted,
            "warm_hit_ratio": round(warm / n, 4),
            "per_replica": {k: per[k] for k in sorted(per)}}


def render(summary):
    lines = []
    m = summary["meta"]
    lines.append(f"timeline: {m.get('events', '?')} events "
                 f"({m.get('dropped', 0)} dropped), "
                 f"{summary['requests']} request records")
    lines.append("")
    lines.append(f"{'phase':<18}{'count':>8}{'total ms':>12}"
                 f"{'mean ms':>10}{'max ms':>10}")
    for name, p in sorted(summary["phases"].items(),
                          key=lambda kv: -kv[1]["total_ms"]):
        lines.append(f"{name:<18}{p['count']:>8}{p['total_ms']:>12}"
                     f"{p['mean_ms']:>10}{p['max_ms']:>10}")
    if summary["slowest_steps"]:
        lines.append("")
        lines.append(f"top {len(summary['slowest_steps'])} slowest steps:")
        for ev in summary["slowest_steps"]:
            extra = {k: v for k, v in ev.items()
                     if k not in ("kind", "name", "dur_ms", "t_ns")}
            lines.append(f"  {ev['dur_ms']:>10.3f} ms  {ev['name']:<16}"
                         f"{json.dumps(extra) if extra else ''}")
    if summary["request_latency"]:
        lines.append("")
        lines.append(f"{'latency':<16}{'count':>7}{'mean':>10}"
                     f"{'p50':>10}{'p95':>10}{'p99':>10}{'max':>10}")
        for name, s in summary["request_latency"].items():
            lines.append(f"{name:<16}{s['count']:>7}{s['mean']:>10}"
                         f"{s['p50']:>10}{s['p95']:>10}{s['p99']:>10}"
                         f"{s['max']:>10}")
    pre = summary.get("prefill")
    if pre:
        lines.append("")
        lines.append(
            f"prefill: occupancy {pre['occupancy']} "
            f"(valid/fed token ratio), variants "
            + ", ".join(f"{k}={v}"
                        for k, v in sorted(pre["variants"].items())))
        lines.append(f"{'bucket':<10}{'chunks':>8}{'mean ms':>10}"
                     f"{'max ms':>10}{'valid tok':>11}{'pad tok':>9}"
                     f"{'occ':>7}")
        for bk, b in pre["per_bucket"].items():
            lines.append(f"{bk:<10}{b['count']:>8}{b['mean_ms']:>10}"
                         f"{b['max_ms']:>10}{b['valid_tokens']:>11}"
                         f"{b['pad_tokens']:>9}{b['occupancy']:>7}")
    dec = summary.get("decode")
    if dec:
        lines.append("")
        lines.append("decode variants:")
        lines.append(f"{'variant':<16}{'steps':>8}{'total ms':>12}"
                     f"{'mean ms':>10}{'max ms':>10}")
        for name, v in sorted(dec["variants"].items(),
                              key=lambda kv: -kv[1]["total_ms"]):
            lines.append(f"{name:<16}{v['count']:>8}{v['total_ms']:>12}"
                         f"{v['mean_ms']:>10}{v['max_ms']:>10}")
        roofed = [(n, v) for n, v in sorted(dec["variants"].items())
                  if v.get("step_us_at_peak_bw")]
        if roofed:
            src = (dec.get("peak_source") or {}).get("hbm_bw", "?")
            lines.append(f"roofline (peak HBM BW "
                         f"{dec.get('peak_hbm_bw', 0) / 1e9:.0f} GB/s, "
                         f"{src}):")
            for name, v in roofed:
                mean_us = v["mean_ms"] * 1e3
                frac = v.get("roofline_frac")
                # %.1f would print interpret-scale fractions as 0.0%
                pct = f"{frac * 100:.3g}%" if frac is not None else "?"
                lines.append(
                    f"  {name}: {mean_us:.1f} us measured, "
                    f"{v['step_us_at_peak_bw']} us at peak BW "
                    f"-> {pct} of roofline "
                    f"({v.get('bytes_per_step_modeled', 0)} modeled "
                    "bytes/step)")
    sched = summary.get("scheduler")
    if sched:
        lines.append("")
        lines.append(f"scheduler: {sched['preemptions']} preemptions, "
                     f"{sched['resumes']} resumes, "
                     f"{sched['deadline_expired']} deadline-expired")
        per = sched.get("per_class_queue_wait_ms", {})
        if per:
            lines.append(f"{'class wait ms':<16}{'count':>7}{'mean':>10}"
                         f"{'p50':>10}{'p95':>10}{'p99':>10}{'max':>10}")
            for cls, s in per.items():
                lines.append(f"{'class ' + cls:<16}{s['count']:>7}"
                             f"{s['mean']:>10}{s['p50']:>10}"
                             f"{s['p95']:>10}{s['p99']:>10}"
                             f"{s['max']:>10}")
        h = sched.get("handoff")
        if h:
            lines.append(
                f"kv handoff: {h['count']} transfers, "
                f"{h['bytes_total']} bytes, p50 "
                f"{h['handoff_ms']['p50']} ms (extract "
                f"{h.get('extract_ms_mean', 0.0)} / put "
                f"{h.get('put_ms_mean', 0.0)} / insert "
                f"{h.get('insert_ms_mean', 0.0)})")
    rt = summary.get("routing")
    if rt:
        lines.append("")
        lines.append(
            f"fleet routing: {rt['requests']} requests, "
            f"warm {rt['warm']} / cold {rt['cold']} "
            f"(warm-hit {rt['warm_hit_ratio']}), "
            f"{rt['diverted']} diverted")
        lines.append(f"{'replica':<18}{'routed':>8}{'share':>9}"
                     f"{'warm':>7}{'diverted':>10}")
        for name, d in rt["per_replica"].items():
            lines.append(f"{name:<18}{d['routed']:>8}{d['share']:>9}"
                         f"{d['warm']:>7}{d['diverted']:>10}")
    return "\n".join(lines)


def summarize_train(meta, events, top=10, gap_factor=4.0,
                    min_wall_ms=50.0):
    """Train-mode summary over ``train_step``/``compile``/``host_gap``
    events: per-phase totals, host-vs-device gap per step, slowest
    steps, compile log. ``host_bound_steps`` applies the SAME predicate
    as the live HostGapDetector (ratio > gap_factor AND wall >=
    min_wall_ms) — the offline diagnosis must not contradict the live
    one on identical data (fast steps have huge ratios but no one
    cares about a 2 ms step)."""
    out = {"meta": {k: meta.get(k) for k in
                    ("schema", "events", "dropped", "mode", "mesh",
                     "accumulate_steps") if k in meta}}
    steps = [ev for ev in events if ev.get("name") == "train_step"]
    phases = {}
    for key in ("stage_ms", "dispatch_ms", "sync_ms"):
        vals = sorted(ev[key] for ev in steps if ev.get(key) is not None)
        if vals:
            phases[key] = {"count": len(vals),
                           "total_ms": round(sum(vals), 3),
                           "mean_ms": round(sum(vals) / len(vals), 3),
                           "p50_ms": round(_percentile(vals, 0.50), 3),
                           "max_ms": round(vals[-1], 3)}
    out["phases"] = phases

    gaps = []
    for ev in steps:
        host = (ev.get("stage_ms") or 0.0) + (ev.get("dispatch_ms")
                                              or 0.0)
        dev = ev.get("sync_ms")
        if dev is None:
            continue
        gaps.append({"step": ev.get("step"),
                     "host_ms": round(host, 3),
                     "device_wait_ms": round(dev, 3),
                     "ratio": round(host / max(dev, 1e-3), 1),
                     "host_bound": (host > gap_factor * max(dev, 1e-3)
                                    and host + dev >= min_wall_ms)})
    # genuinely host-bound steps first (then by host time): sorting on
    # raw ratio would bury the one real 3 s host-bound step under a
    # pile of trivially fast steps whose sync rounds to ~0
    gaps.sort(key=lambda g: (not g["host_bound"], -g["host_ms"]))
    out["host_device_gap"] = {
        "steps": len(gaps),
        "host_bound_steps": sum(1 for g in gaps if g["host_bound"]),
        "worst": gaps[:top]}

    timed = [ev for ev in steps if ev.get("dur_ms") is not None]
    timed.sort(key=lambda e: -e["dur_ms"])
    out["slowest_steps"] = timed[:top]
    out["compiles"] = [{k: ev.get(k) for k in
                        ("program", "dur_ms", "count")}
                       for ev in events if ev.get("name") == "compile"]
    out["host_gap_events"] = sum(1 for ev in events
                                 if ev.get("name") == "host_gap")
    out["stalls"] = [ev.get("reason") for ev in events
                     if ev.get("name") == "stall"]
    return out


def render_train(summary):
    lines = []
    m = summary["meta"]
    lines.append(f"train timeline: {m.get('events', '?')} events "
                 f"({m.get('dropped', 0)} dropped), mesh="
                 f"{m.get('mesh')}")
    lines.append("")
    lines.append(f"{'phase':<14}{'count':>7}{'total ms':>12}"
                 f"{'mean ms':>10}{'p50 ms':>10}{'max ms':>10}")
    for name, p in summary["phases"].items():
        lines.append(f"{name:<14}{p['count']:>7}{p['total_ms']:>12}"
                     f"{p['mean_ms']:>10}{p['p50_ms']:>10}"
                     f"{p['max_ms']:>10}")
    g = summary["host_device_gap"]
    lines.append("")
    lines.append(f"host-vs-device: {g['host_bound_steps']}/{g['steps']} "
                 "steps host-bound")
    for w in g["worst"][:5]:
        lines.append(f"  step {w['step']}: host {w['host_ms']} ms vs "
                     f"device wait {w['device_wait_ms']} ms "
                     f"({w['ratio']}x)")
    if summary["compiles"]:
        lines.append("")
        lines.append("compiles:")
        for c in summary["compiles"]:
            lines.append(f"  {c.get('program')}: {c.get('dur_ms'):.1f} ms"
                         f" (#{c.get('count')})")
    if summary["slowest_steps"]:
        lines.append("")
        lines.append(f"top {len(summary['slowest_steps'])} slowest steps:")
        for ev in summary["slowest_steps"]:
            lines.append(f"  {ev['dur_ms']:>10.3f} ms  step "
                         f"{ev.get('step')}")
    if summary["stalls"]:
        lines.append("")
        lines.append(f"stalls: {len(summary['stalls'])}")
        for r in summary["stalls"][:5]:
            lines.append(f"  {r}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="timeline JSONL file")
    ap.add_argument("--mode", choices=("auto", "serving", "train"),
                    default="auto",
                    help="summary flavor (auto reads the meta header)")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest steps to list (default 10)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of a table")
    args = ap.parse_args(argv)
    try:
        meta, events, requests = load(args.path)
    except TimelineError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    mode = args.mode
    if mode == "auto":
        mode = meta.get("mode", "serving")
    if mode == "train":
        summary = summarize_train(meta, events, top=args.top)
        print(json.dumps(summary, indent=1) if args.json
              else render_train(summary))
    else:
        summary = summarize(meta, events, requests, top=args.top)
        print(json.dumps(summary, indent=1) if args.json
              else render(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
